"""PR-10 network plane: binary v2 wire frames, pooled connections,
concurrent fan-out, and pool-backed remote CNs.

Covers the four layers together because their contracts interlock:
the v2 wire must roundtrip every handler payload value-identically, the
ConnPool must never hand out a socket desynced by a timed-out call, the
fan-out must keep results roster-ordered so survey sums and VN
transcripts stay byte-identical to the old serial loops, and a remote
CN holding a warm CryptoPool must consume DRO slabs instead of
precomputing (ROADMAP item 5's remaining gap). scripts/bench_net_plane.py
measures the same claims; this file proves them.
"""
import json
import socket
import threading
import time

import numpy as np
import pytest

from drynx_tpu.proofs import requests as rq
from drynx_tpu.resilience import policy as rp
from drynx_tpu.resilience.faults import FaultPlan, FaultSpec, set_fault_plan
from drynx_tpu.service import transport as tp
from drynx_tpu.service.node import (DrynxNode, RemoteClient, Roster,
                                    RosterEntry, call_entry, fan_out)
from drynx_tpu.service.transport import (CallTimeout, Conn, ConnPool,
                                         LinkModel, NodeServer,
                                         decode_frame, encode_frame,
                                         jsonable, pack_array,
                                         set_conn_pool, unb64, unpack_array)


@pytest.fixture(autouse=True)
def _clean_process_globals():
    """Transport and pool state is process-global by design; tests must
    not leak negotiated sockets, fault plans, or an active CryptoPool
    into each other."""
    from drynx_tpu import pool as pool_mod

    set_fault_plan(None)
    set_conn_pool(None)
    pool_mod.activate(None)
    yield
    set_fault_plan(None)
    set_conn_pool(None)
    pool_mod.activate(None)


def _listify(o):
    """Tuples arrive as JSON lists on either wire; normalize for
    equality checks against the decoded tree."""
    if isinstance(o, dict):
        return {k: _listify(v) for k, v in o.items()}
    if isinstance(o, (list, tuple)):
        return [_listify(v) for v in o]
    return o


def _pack_bytes(b: bytes) -> dict:
    return pack_array(np.frombuffer(b, dtype=np.uint8))


def _handler_payloads() -> dict:
    """One representative message per handler payload family in
    service/node.py — every shape the wire must carry."""
    rng = np.random.default_rng(7)
    cts = rng.integers(0, 2 ** 16, size=(4, 2, 3, 16)).astype(np.uint32)
    pts = rng.integers(0, 2 ** 16, size=(4, 3, 16)).astype(np.uint32)
    A = rng.integers(0, 2 ** 16, size=(2, 16, 3, 2, 16)).astype(np.uint32)
    roster = Roster([RosterEntry(name="cn0", role="cn", host="127.0.0.1",
                                 port=7000, public=(12345, 67890))])
    return {
        "set_roster": {"type": "set_roster", "roster": roster.to_dict()},
        "survey_query": {
            "type": "survey_query", "op": "sum", "survey_id": "s1",
            "query_min": 0, "query_max": 9, "proofs": True,
            "ranges": [[16, 4], [16, 4]], "obfuscation": False,
            "diffp": {"noise_list_size": 8, "lap_mean": 0.0,
                      "lap_scale": 2.0, "quanta": 1.0, "scale": 1.0,
                      "limit": 4.0},
            "lr_params": None, "group_by": None, "range_offset": 0,
            "min_dp_quorum": 0, "dp_exclude": [],
            "client_pub": [12345, 67890]},
        "survey_dp": {
            "type": "survey_dp", "op": "sum", "survey_id": "s1",
            "query_min": 0, "query_max": 9, "range_offset": 0,
            "proofs": True, "ranges": [[16, 4]],
            # the nested range_sigs blob: publics per CN + stacked A tables
            "range_sigs": {"16": {"pubs": [[1, 2], [3, 4]],
                                  "A": pack_array(A)}}},
        "survey_dp_reply": {"type": "survey_dp_reply",
                            "cts": pack_array(cts)},
        "range_sig_reply": {"type": "range_sig_reply", "pub": [111, 222],
                            "A": pack_array(A[0])},
        "contrib": {"type": "shuffle_contrib", "survey_id": "s1",
                    "proofs": False, "cts": pack_array(cts)},
        "ks_contrib": {"type": "ks_contrib", "survey_id": "s1",
                       "proofs": False, "client_pub": [12345, 67890],
                       "k_component": pack_array(pts)},
        "ks_reply": {"type": "ks_contrib_reply", "u": pack_array(pts),
                     "w": pack_array(pts)},
        "proof_request": {
            "type": "proof_request", "proof_type": "range",
            "survey_id": "s1", "sender_id": "dp0",
            "differ_info": "range-dp0", "round_id": 0,
            "data": _pack_bytes(b"\x00\x01\xfe\xff" * 64),
            "signature": _pack_bytes(b"\x80" * 96)},
        "end_verification_reply": {
            "type": "end_verification_reply", "block_index": 1,
            "block_hash": "ab" * 32,
            "bitmap": {"vn0:range-dp0": "BM_TRUE", "vn1:ks-cn0": "BM_TRUE"},
            "vn_reported": ["vn0", "vn1"], "vn_absent": []},
        "get_proofs_reply": {
            "type": "get_proofs_reply",
            "proofs": {"range-dp0": _pack_bytes(b"\x01\x02" * 100)}},
    }


# ---------------------------------------------------------------------------
# v2 wire format
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(_handler_payloads()))
def test_v2_roundtrip_every_handler_payload(name):
    """encode/decode under v2 returns the identical value tree (bytes stay
    bytes); v1 returns the jsonable() form (bytes as base64); packed
    arrays reconstruct bit-identically under both wires."""
    msg = _handler_payloads()[name]
    f2 = encode_frame(msg, 2)
    dec2 = decode_frame(f2[4:], 2)
    assert dec2 == _listify(msg)
    f1 = encode_frame(msg, 1)
    dec1 = decode_frame(f1[4:], 1)
    assert dec1 == jsonable(msg)

    def arrays(tree, out):
        if isinstance(tree, dict):
            if set(tree) >= {"dtype", "shape", "data"}:
                out.append(tree)
            else:
                for v in tree.values():
                    arrays(v, out)
        elif isinstance(tree, list):
            for v in tree:
                arrays(v, out)
        return out

    for a2, a1, a0 in zip(arrays(dec2, []), arrays(dec1, []),
                          arrays(msg, [])):
        want = unpack_array(a0)
        assert np.array_equal(unpack_array(a2), want)
        assert np.array_equal(unpack_array(a1), want)


def test_v2_frames_beat_v1_on_tensor_payloads():
    """Base64 inflates tensor payloads ~33%; v2 ships raw segments, so a
    ciphertext frame must come in >=20% smaller (the bench asserts the
    25% end-to-end bar over a whole survey)."""
    msg = _handler_payloads()["survey_dp_reply"]
    v1, v2 = len(encode_frame(msg, 1)), len(encode_frame(msg, 2))
    assert v2 < 0.8 * v1
    # tiny control messages may not shrink, but must stay comparable
    ping = {"type": "ping"}
    assert len(encode_frame(ping, 2)) <= len(encode_frame(ping, 1)) + 16


def test_v2_decode_rejects_garbage():
    from drynx_tpu.service.transport import CorruptFrame

    good = encode_frame({"a": b"xy"}, 2)[4:]
    for bad in (b"", b"\x00" * 6, b"\xff" + good[1:], good[:-1]):
        with pytest.raises(CorruptFrame):
            decode_frame(bad, 2)
    assert unb64(b"raw") == b"raw" and unb64("cmF3") == b"raw"


# ---------------------------------------------------------------------------
# wire negotiation
# ---------------------------------------------------------------------------

def test_wire_negotiation_default_v2_and_kill_switch(monkeypatch):
    srv = NodeServer()
    srv.register("echo", lambda m: {"blob": m["blob"]})
    srv.start()
    try:
        c = Conn(srv.host, srv.port)
        assert c.wire == 2
        r = c.call({"type": "echo", "blob": b"\x00\xff" * 8})
        assert r["blob"] == b"\x00\xff" * 8      # raw bytes end to end
        c.close()

        monkeypatch.setenv("DRYNX_WIRE", "json")
        c1 = Conn(srv.host, srv.port)
        assert c1.wire == 1
        r1 = c1.call({"type": "echo", "blob": b"\x00\xff" * 8})
        assert unb64(r1["blob"]) == b"\x00\xff" * 8   # base64 on v1
        c1.close()
    finally:
        srv.stop()


def test_wire_negotiation_old_server_stays_v1():
    """A pre-v2 server has no wire_hello handling and replies with a
    handler error; the client must stay on v1 and keep working."""
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)

    def serve():
        conn, _ = lsock.accept()
        with conn:
            while True:
                msg = tp.recv_msg(conn)
                if msg is None:
                    return
                if msg.get("type") == "wire_hello":
                    tp.send_msg(conn, {"type": "error",
                                       "error": "no handler for "
                                                "'wire_hello'"})
                else:
                    tp.send_msg(conn, {"type": "echo_reply",
                                       "v": msg["v"]})

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    try:
        c = Conn(*lsock.getsockname())
        assert c.wire == 1
        assert c.call({"type": "echo", "v": 42})["v"] == 42
        c.close()
    finally:
        lsock.close()


# ---------------------------------------------------------------------------
# connection pool
# ---------------------------------------------------------------------------

def _echo_server():
    srv = NodeServer()
    srv.register("echo", lambda m: {"v": m["v"]})
    srv.register("slow", lambda m: (time.sleep(0.6), {"ok": True})[1])
    srv.start()
    return srv


def test_conn_pool_reuses_and_bounds_idle():
    srv = _echo_server()
    pool = ConnPool(max_idle=2)
    try:
        c1 = pool.get(srv.host, srv.port, peer="s")
        assert c1.call({"type": "echo", "v": 1})["v"] == 1
        pool.put(c1)
        c2 = pool.get(srv.host, srv.port, peer="s")
        assert c2 is c1                       # reused, not re-dialed
        st = pool.stats()
        assert st["connects"] == 1 and st["reuses"] == 1
        extra = [pool.get(srv.host, srv.port, peer="s") for _ in range(3)]
        for c in [c2] + extra:
            pool.put(c)
        assert pool.idle_count() == 2         # bounded at max_idle
        pool.close_all()
        assert pool.idle_count() == 0
    finally:
        srv.stop()


def test_conn_pool_global_cap_evicts_lru(monkeypatch):
    """max_total bounds idle sockets across ALL keys: at the cap a put
    closes the globally least-recently-pooled connection first, so a
    256-peer roster's cold sockets age out while warm ones survive.
    DRYNX_CONN_POOL_MAX overrides the policy default per process."""
    s1, s2 = _echo_server(), _echo_server()
    pool = ConnPool(max_idle=4, max_total=2)
    try:
        a1 = pool.get(s1.host, s1.port, peer="a")
        a2 = pool.get(s1.host, s1.port, peer="a")
        b1 = pool.get(s2.host, s2.port, peer="b")
        pool.put(a1)                     # oldest stamp -> LRU victim
        pool.put(b1)
        assert pool.idle_count() == 2 and pool.stats()["evictions"] == 0
        pool.put(a2)                     # at cap: a1 ages out, b1 stays
        st = pool.stats()
        assert st["evictions"] == 1 and st["idle"] == 2
        assert a1.closed and not b1.closed
        assert pool.get(s1.host, s1.port, peer="a") is a2
        assert pool.get(s2.host, s2.port, peer="b") is b1
        pool.close_all()
    finally:
        s1.stop()
        s2.stop()
    monkeypatch.setenv("DRYNX_CONN_POOL_MAX", "3")
    assert ConnPool().max_total == 3
    monkeypatch.delenv("DRYNX_CONN_POOL_MAX")
    assert ConnPool().max_total == rp.CONN_POOL_MAX


def test_conn_pool_never_reuses_timed_out_conn():
    """The half-read bugfix: a CallTimeout leaves the reply in flight; the
    broken conn must never be pooled, and the next checkout must get a
    FRESH socket that answers the new request (not the stale reply)."""
    srv = _echo_server()
    pool = ConnPool()
    try:
        c = pool.get(srv.host, srv.port, timeout=0.1, peer="s")
        with pytest.raises(CallTimeout):
            c.call({"type": "slow"})
        assert c.broken
        pool.put(c)                           # refused: discarded
        assert pool.idle_count() == 0
        c2 = pool.get(srv.host, srv.port, timeout=5.0, peer="s")
        assert c2 is not c
        assert c2.call({"type": "echo", "v": 7})["v"] == 7
        pool.put(c2)
    finally:
        srv.stop()


def test_conn_pool_health_check_discards_desynced_socket():
    """A pooled socket with buffered bytes (a reply that landed after its
    caller gave up without breaking the conn) fails the MSG_PEEK health
    check on checkout."""
    srv = _echo_server()
    pool = ConnPool()
    try:
        c = pool.get(srv.host, srv.port, peer="s")
        assert c.call({"type": "echo", "v": 0})["v"] == 0
        # push a request and abandon the reply: conn not broken, but the
        # socket now holds a stale frame
        tp.send_frame(c.sock, {"type": "echo", "v": 1}, c.wire)
        deadline = time.time() + 5.0
        while time.time() < deadline:       # wait for the reply to buffer
            try:
                c.sock.setblocking(False)
                c.sock.recv(1, socket.MSG_PEEK)
                break
            except BlockingIOError:
                time.sleep(0.01)
            finally:
                c.sock.settimeout(5.0)
        pool.put(c)
        assert pool.idle_count() == 1
        c2 = pool.get(srv.host, srv.port, peer="s")
        assert c2 is not c                    # desynced one was discarded
        assert pool.stats()["discards"] >= 1
        assert c2.call({"type": "echo", "v": 9})["v"] == 9
        pool.put(c2)
    finally:
        srv.stop()


def test_conn_pool_purges_suspect_peer_stack_on_redial():
    """PR-17 satellite: a conn that breaks mid-exchange marks its peer
    suspect. The peer's remaining pooled sockets can still pass MSG_PEEK
    (a cut link never delivers a FIN), so a suspect key must bypass its
    idle stack — and once a FRESH dial succeeds (the peer is
    demonstrably back), the stale stack is purged rather than handed
    out to burn one call timeout each."""
    srv = _echo_server()
    pool = ConnPool(max_idle=4)
    try:
        a = pool.get(srv.host, srv.port, peer="s")
        b = pool.get(srv.host, srv.port, peer="s")
        c = pool.get(srv.host, srv.port, peer="s")
        for x in (a, b):
            assert x.call({"type": "echo", "v": 0})["v"] == 0
            pool.put(x)
        assert pool.idle_count() == 2
        # c breaks mid-exchange (timeout on a slow handler): peer suspect
        c._timeout = 0.1
        c.sock.settimeout(0.1)
        with pytest.raises(CallTimeout):
            c.call({"type": "slow"})
        pool.discard(c)
        # a and b still sit idle and still look healthy — but the next
        # checkout must NOT trust them: fresh dial, stale stack purged
        d = pool.get(srv.host, srv.port, peer="s")
        assert d is not a and d is not b
        st = pool.stats()
        assert st["purges"] == 2 and pool.idle_count() == 0
        assert a.closed and b.closed
        assert d.call({"type": "echo", "v": 5})["v"] == 5
        pool.put(d)
        # suspicion cleared: the pooled socket is trusted again
        assert pool.get(srv.host, srv.port, peer="s") is d
        pool.close_all()
    finally:
        srv.stop()


def test_conn_pool_overflow_close_does_not_condemn_peer():
    """Idle-depth overflow closes a healthy surplus conn; that must not
    mark the peer suspect (no purge storm on a busy healthy peer)."""
    srv = _echo_server()
    pool = ConnPool(max_idle=1)
    try:
        a = pool.get(srv.host, srv.port, peer="s")
        b = pool.get(srv.host, srv.port, peer="s")
        pool.put(a)
        pool.put(b)            # overflow: closed, NOT suspect
        assert pool.idle_count() == 1
        c = pool.get(srv.host, srv.port, peer="s")
        assert c is a          # the pooled socket is still trusted
        assert pool.stats()["purges"] == 0
        pool.put(c)
        pool.close_all()
    finally:
        srv.stop()


def test_call_entry_checks_out_of_process_pool():
    srv = _echo_server()
    try:
        pool = ConnPool()
        set_conn_pool(pool)
        e = RosterEntry(name="s", role="cn", host=srv.host, port=srv.port,
                        public=(0, 0))
        for v in range(3):
            assert call_entry(e, {"type": "echo", "v": v})["v"] == v
        st = pool.stats()
        assert st["connects"] == 1 and st["reuses"] == 2
    finally:
        set_conn_pool(None)
        srv.stop()


# ---------------------------------------------------------------------------
# concurrent fan-out
# ---------------------------------------------------------------------------

def test_fan_out_results_stay_roster_ordered():
    entries = [RosterEntry(name=f"n{i}", role="dp", host="x", port=i,
                           public=(0, 0)) for i in range(6)]

    def call(e, m):
        # later roster entries answer FIRST: completion order is the
        # reverse of roster order, results must not be
        time.sleep((len(entries) - e.port) * 0.02)
        if e.port == 3:
            raise OSError("down")
        return {"who": e.name, "echo": m["k"]}

    outs = fan_out(entries, lambda e: {"k": e.port * 10}, call=call)
    assert len(outs) == 6
    for i, (r, err) in enumerate(outs):
        if i == 3:
            assert r is None and isinstance(err, OSError)
        else:
            assert err is None
            assert r == {"who": f"n{i}", "echo": i * 10}


def test_fan_out_serial_env_matches_parallel(monkeypatch):
    entries = [RosterEntry(name=f"n{i}", role="dp", host="x", port=i,
                           public=(0, 0)) for i in range(4)]

    def call(e, m):
        return e.port * 2

    par = fan_out(entries, lambda e: {}, call=call)
    monkeypatch.setenv("DRYNX_FANOUT", "serial")
    ser = fan_out(entries, lambda e: {}, call=call)
    assert par == ser == [(0, None), (2, None), (4, None), (6, None)]
    monkeypatch.setenv("DRYNX_FANOUT_WORKERS", "2")
    monkeypatch.delenv("DRYNX_FANOUT")
    assert fan_out(entries, lambda e: {}, call=call) == par


def test_fan_out_overlaps_link_latency():
    """The point of the tentpole: n concurrent calls over a latency-bound
    link cost ~max, not ~sum."""
    entries = [RosterEntry(name=f"n{i}", role="dp", host="x", port=i,
                           public=(0, 0)) for i in range(6)]

    def call(e, m):
        time.sleep(0.1)
        return e.name

    t0 = time.perf_counter()
    outs = fan_out(entries, lambda e: {}, call=call, workers=6)
    par = time.perf_counter() - t0
    assert [r for r, _ in outs] == [e.name for e in entries]
    t0 = time.perf_counter()
    fan_out(entries, lambda e: {}, call=call, workers=1)
    ser = time.perf_counter() - t0
    assert par < ser / 2           # 6x0.1s serial vs ~0.1s overlapped


# ---------------------------------------------------------------------------
# fault-plan determinism + link accounting under concurrency
# ---------------------------------------------------------------------------

def test_fault_plan_draws_are_arrival_order_independent():
    """Per-(spec, target, seq) keyed draws: the verdict map over (target,
    event#) must be identical whether events arrive serially in roster
    order or interleaved across threads in reverse."""
    targets = [f"dp{i}" for i in range(5)]
    events = 8

    def specs():
        return [FaultSpec(where="connect", kind="refuse", target="dp*",
                          prob=0.5),
                FaultSpec(where="request", kind="drop", target="dp*",
                          mtype="survey_dp", prob=0.4)]

    serial = FaultPlan(seed=11, specs=specs())
    want = {}
    for t in targets:
        for k in range(events):
            want[("connect", t, k)] = serial.pick("connect", t) is not None
            want[("request", t, k)] = (
                serial.pick("request", t, "survey_dp") is not None)

    threaded = FaultPlan(seed=11, specs=specs())
    got = {}
    lock = threading.Lock()

    def worker(t):
        for k in range(events):
            a = threaded.pick("connect", t) is not None
            b = threaded.pick("request", t, "survey_dp") is not None
            with lock:
                got[("connect", t, k)] = a
                got[("request", t, k)] = b

    threads = [threading.Thread(target=worker, args=(t,))
               for t in reversed(targets)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert got == want


def test_fault_plan_count_caps_are_per_target():
    """A count cap must be a per-(spec, target) budget, not a global one a
    fast thread can drain from under the others."""
    plan = FaultPlan(seed=0, specs=[FaultSpec(where="connect", kind="refuse",
                                              target="dp*", prob=1.0,
                                              count=2)])
    fired = {t: sum(plan.pick("connect", t) is not None for _ in range(5))
             for t in ("dp0", "dp1", "dp2")}
    assert fired == {"dp0": 2, "dp1": 2, "dp2": 2}
    assert plan.specs[0].fired == 6


def test_link_model_concurrent_charges_account_exactly():
    m = LinkModel()          # no delay: pure accounting
    threads = [threading.Thread(
        target=lambda i=i: [m.charge(3, peer=f"p{i % 2}")
                            for _ in range(200)]) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    st = m.stats()
    assert st["bytes_total"] == 8 * 200 * 3
    assert st["msgs_total"] == 8 * 200
    assert st["by_peer"] == {"p0": 2400, "p1": 2400}
    m.reset_stats()
    assert m.stats() == {"bytes_total": 0, "msgs_total": 0, "by_peer": {}}


# ---------------------------------------------------------------------------
# end-to-end: parallel == serial, v2 < v1, pooled conns
# ---------------------------------------------------------------------------

def _boot_roster(tmp_path, roles, seed=21):
    rng = np.random.default_rng(seed)
    nodes, entries, datas = [], [], []
    for i, role in enumerate(roles):
        x, pub = eg_keygen(rng)
        data = None
        if role == "dp":
            data = rng.integers(0, 10, size=(8,)).astype(np.int64)
            datas.append(data)
        n = DrynxNode(f"{role}{i}", x, pub, data=data,
                      db_path=str(tmp_path / f"{role}{i}.db"))
        n.start()
        entries.append(RosterEntry(name=f"{role}{i}", role=role,
                                   host=n.address[0], port=n.address[1],
                                   public=pub))
        nodes.append(n)
    return nodes, entries, datas, rng


def eg_keygen(rng):
    from drynx_tpu.crypto import elgamal as eg

    return eg.keygen(rng)


def test_survey_parallel_serial_v1_v2_pooled_all_agree(tmp_path,
                                                       monkeypatch):
    """One roster, four wire/dispatch variants of the same sum survey:
    serial-v2, parallel-v2, parallel-v1, parallel-v2-pooled. All four
    must return the exact sum with the same responder list; serial and
    parallel (pool off) must account byte-identical traffic; v1 must
    cost strictly more bytes than v2; the pooled run must reuse sockets."""
    from drynx_tpu.crypto import elgamal as eg

    nodes, entries, datas, rng = _boot_roster(
        tmp_path, ["cn", "cn", "dp", "dp", "dp"])
    roster = Roster(entries)
    client = RemoteClient(roster, rng)
    client.broadcast_roster()
    # frequency_count: 10 outputs -> real tensor payloads on the wire
    # (a 1-value sum survey is all JSON header, no byte-saving signal)
    want = {v: int(c) for v, c in
            enumerate(np.bincount(np.concatenate(datas), minlength=10))}
    dl = eg.DecryptionTable(limit=500)

    def run(sid):
        set_conn_pool(None)   # fresh sockets: each variant negotiates anew
        r = client.run_survey("frequency_count", query_min=0, query_max=9,
                              survey_id=sid, dlog=dl)
        return r, dict(client.last_net), list(client.last_responders)

    try:
        # pool off for the byte-identity pair: every call dials fresh, so
        # serial and parallel runs exchange the same frame multiset
        monkeypatch.setenv("DRYNX_CONN_POOL", "off")
        monkeypatch.setenv("DRYNX_FANOUT", "serial")
        res_ser, net_ser, resp_ser = run("sv-ser")    # also warms compiles
        monkeypatch.delenv("DRYNX_FANOUT")
        res_par, net_par, resp_par = run("sv-par")
        monkeypatch.setenv("DRYNX_WIRE", "json")
        res_v1, net_v1, resp_v1 = run("sv-v1")
        monkeypatch.delenv("DRYNX_WIRE")
        monkeypatch.delenv("DRYNX_CONN_POOL")
        res_pool, _net_pool, resp_pool = run("sv-pool")
        # second survey over the SAME pool: every peer was dialed once
        # already, so this run must ride reused sockets (no reconnects,
        # no wire hellos)
        res_pool2 = client.run_survey("frequency_count", query_min=0,
                                      query_max=9, survey_id="sv-pool2",
                                      dlog=dl)
        net_pool2 = dict(client.last_net)
        pool_stats = tp.conn_pool().stats()
    finally:
        set_conn_pool(None)
        for n in nodes:
            n.stop()

    for res in (res_ser, res_par, res_v1, res_pool, res_pool2):
        assert {int(k): int(v) for k, v in res.items()} == want
    assert resp_ser == resp_par == resp_v1 == resp_pool \
        == ["dp2", "dp3", "dp4"]
    # dispatch order must not change what crosses the wire
    assert net_ser["bytes_total"] == net_par["bytes_total"]
    assert net_ser["msgs_total"] == net_par["msgs_total"]
    assert net_ser["by_peer"] == net_par["by_peer"]
    # binary frames: the same survey costs >=20% fewer bytes than JSON
    # (bench_net_plane asserts the 25% bar on the bigger roster)
    assert net_par["bytes_total"] < 0.8 * net_v1["bytes_total"]
    # per-peer accounting is surfaced per survey: every dialed node shows
    assert {"cn0", "dp2", "dp3", "dp4"} <= set(net_par["by_peer"])
    # warm pool: the second pooled survey reuses sockets and skips the
    # per-connection hello traffic the unpooled variant pays
    assert pool_stats["reuses"] > 0
    assert net_pool2["bytes_total"] < net_par["bytes_total"]


@pytest.mark.slow
def test_survey_transcripts_parallel_vs_serial_identical(tmp_path,
                                                         monkeypatch):
    """Proofs-on: the committed VN audit bitmap (keys + verdict codes)
    must be byte-identical between serial and parallel dispatch — the
    fan-out may reorder arrivals, never the transcript."""
    from drynx_tpu.crypto import elgamal as eg

    nodes, entries, datas, rng = _boot_roster(
        tmp_path, ["cn", "cn", "dp", "vn", "vn"], seed=33)
    roster = Roster(entries)
    client = RemoteClient(roster, rng)
    client.broadcast_roster()
    dl = eg.DecryptionTable(limit=500)

    def run(sid):
        set_conn_pool(None)
        result, block = client.run_survey(
            "sum", query_min=0, query_max=9, proofs=True, ranges=[(4, 4)],
            survey_id=sid, dlog=dl, timeout=2400.0)

        def norm(bm):
            # strip the per-survey id so serial/parallel keys align
            return {k.replace(sid, "SID"): v for k, v in bm.items()}

        return result, json.dumps(norm(block["bitmap"]), sort_keys=True)

    try:
        monkeypatch.setenv("DRYNX_FANOUT", "serial")
        res_ser, tr_ser = run("tr-ser")
        monkeypatch.delenv("DRYNX_FANOUT")
        res_par, tr_par = run("tr-par")
    finally:
        set_conn_pool(None)
        for n in nodes:
            n.stop()

    assert res_ser == res_par == int(sum(d.sum() for d in datas))
    assert tr_ser == tr_par
    bm = json.loads(tr_par)
    assert bm and set(bm.values()) == {rq.BM_TRUE}


# ---------------------------------------------------------------------------
# pool-backed remote CNs (ROADMAP item 5, remaining gap)
# ---------------------------------------------------------------------------

def test_remote_cn_shuffle_consumes_pooled_dro(tmp_path):
    """A DrynxNode constructed with a warm CryptoPool serves
    shuffle_contrib from DRO slabs: zero fresh precompute, exactly
    dro_need elements consumed, and the noise multiset survives."""
    import jax
    import jax.numpy as jnp

    from drynx_tpu import pool as pool_mod
    from drynx_tpu.crypto import elgamal as eg
    from drynx_tpu.parallel import dro
    from drynx_tpu.pool import replenish

    rng = np.random.default_rng(5)
    x, pub = eg.keygen(rng)
    need = 8
    pool = pool_mod.CryptoPool(str(tmp_path), slab_elems=need)
    node = DrynxNode("cn0", x, pub, db_path=str(tmp_path / "cn0.db"),
                     pool=pool)
    node.start()
    try:
        node.roster = Roster([RosterEntry(name="cn0", role="cn",
                                          host="127.0.0.1", port=0,
                                          public=pub)])
        tbl = node._pub_table(node.roster.collective_pub())
        replenish.refill_to(pool, jax.random.PRNGKey(1), tbl.table, need)

        noise = np.array([0, 1, -1, 2, -2, 0, 1, -1], dtype=np.int64)
        cts = dro.encrypt_noise(jax.random.PRNGKey(2), tbl, noise)
        before = dro.PRECOMPUTE_CALLS
        r = node._h_shuffle_contrib({"type": "shuffle_contrib",
                                     "survey_id": "s", "proofs": False,
                                     "cts": pack_array(np.asarray(cts))})
        assert dro.PRECOMPUTE_CALLS == before      # pooled: no fresh build
        assert pool.counters["elements_consumed"] == need

        out = jnp.asarray(unpack_array(r["cts"]))
        vals, found = eg.decrypt_ints(out, x, eg.DecryptionTable(limit=8))
        assert bool(np.all(np.asarray(found)))
        assert np.array_equal(np.sort(np.asarray(vals)), np.sort(noise))

        # drained pool: the same handler falls back to one fresh precompute
        before = dro.PRECOMPUTE_CALLS
        node._h_shuffle_contrib({"type": "shuffle_contrib",
                                 "survey_id": "s2", "proofs": False,
                                 "cts": pack_array(np.asarray(cts))})
        assert dro.PRECOMPUTE_CALLS == before + 1
    finally:
        node.stop()


def test_remote_diffp_survey_runs_on_pooled_dro(tmp_path, monkeypatch):
    """End-to-end TCP diffp survey with pool-holding CN processes: the
    whole shuffle chain consumes slabs (PRECOMPUTE_CALLS flat,
    elements_consumed == per-CN need x n_cns) and the noisy sum stays
    within the configured limit."""
    import jax

    from drynx_tpu import pool as pool_mod
    from drynx_tpu.crypto import elgamal as eg
    from drynx_tpu.parallel import dro
    from drynx_tpu.pool import replenish

    S = 8
    pool = pool_mod.CryptoPool(str(tmp_path / "pool"), slab_elems=S)
    rng = np.random.default_rng(9)
    nodes, entries, datas = [], [], []
    for i, role in enumerate(["cn", "cn", "dp", "dp"]):
        x, pub = eg.keygen(rng)
        data = None
        if role == "dp":
            data = np.arange(4, dtype=np.int64)
            datas.append(data)
        n = DrynxNode(f"{role}{i}", x, pub, data=data,
                      db_path=str(tmp_path / f"{role}{i}.db"),
                      pool=pool if role == "cn" else None)
        n.start()
        entries.append(RosterEntry(name=f"{role}{i}", role=role,
                                   host=n.address[0], port=n.address[1],
                                   public=pub))
        nodes.append(n)
    roster = Roster(entries)
    client = RemoteClient(roster, rng)
    client.broadcast_roster()

    coll_tbl = eg.pub_table(roster.collective_pub())
    replenish.refill_to(pool, jax.random.PRNGKey(3), coll_tbl.table,
                        S * 2)                       # one slab per CN
    diffp = {"noise_list_size": S, "lap_mean": 0.0, "lap_scale": 2.0,
             "quanta": 1.0, "scale": 1.0, "limit": 4.0}
    before = dro.PRECOMPUTE_CALLS
    try:
        res = client.run_survey("sum", query_min=0, query_max=5,
                                survey_id="sv-diffp", diffp=diffp,
                                dlog=eg.DecryptionTable(limit=2000))
    finally:
        set_conn_pool(None)
        for n in nodes:
            n.stop()
    assert dro.PRECOMPUTE_CALLS == before            # fully pooled
    assert pool.counters["elements_consumed"] == S * 2
    want = int(sum(d.sum() for d in datas))
    assert abs(res - want) <= diffp["limit"]
