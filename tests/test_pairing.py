"""Device Fp2/Fp12/G2/pairing kernels vs the pure-Python oracle."""
import numpy as np
import jax.numpy as jnp
import pytest

from drynx_tpu.crypto import fp2 as F2
from drynx_tpu.crypto import fp12 as F12
from drynx_tpu.crypto import g2 as G2
from drynx_tpu.crypto import pairing as PAIR
from drynx_tpu.crypto import curve as C
from drynx_tpu.crypto import field as F
from drynx_tpu.crypto import params, refimpl

pytestmark = pytest.mark.slow  # heavy compiles; fast tier = -m 'not slow'

RNG = np.random.default_rng(41)


def rand_fp():
    return int.from_bytes(RNG.bytes(40), "little") % params.P


def rand_fp2():
    return (rand_fp(), rand_fp())


def rand_fp12():
    return tuple(rand_fp2() for _ in range(6))


def test_fp2_ops_match_oracle():
    a, b = rand_fp2(), rand_fp2()
    da, db = jnp.asarray(F2.from_ref(a)), jnp.asarray(F2.from_ref(b))
    assert F2.to_ref(F2.add(da, db)) == refimpl.fp2_add(a, b)
    assert F2.to_ref(F2.sub(da, db)) == refimpl.fp2_sub(a, b)
    assert F2.to_ref(F2.mul(da, db)) == refimpl.fp2_mul(a, b)
    assert F2.to_ref(F2.sqr(da)) == refimpl.fp2_sq(a)
    assert F2.to_ref(F2.inv(da)) == refimpl.fp2_inv(a)
    assert F2.to_ref(F2.mul_xi(da)) == refimpl.fp2_mul(a, params.XI)


def test_fp12_mul_inv_match_oracle():
    a, b = rand_fp12(), rand_fp12()
    da, db = jnp.asarray(F12.from_ref(a)), jnp.asarray(F12.from_ref(b))
    assert F12.to_ref(F12.mul(da, db)) == refimpl.fp12_mul(a, b)
    assert F12.to_ref(F12.conj6(da)) == refimpl.fp12_conj6(a)
    got_inv = F12.to_ref(F12.inv(da))
    assert refimpl.fp12_mul(got_inv, a) == refimpl.FP12_ONE


def test_fp12_pow_matches_oracle():
    a = rand_fp12()
    da = jnp.asarray(F12.from_ref(a))
    e = 0xDEADBEEFCAFE
    assert F12.to_ref(F12.pow_const(da, e)) == refimpl.fp12_pow(a, e)


def test_g2_group_law_matches_oracle():
    k1, k2 = 12345, 987654321
    P1 = refimpl.g2_mul(refimpl.G2, k1)
    P2 = refimpl.g2_mul(refimpl.G2, k2)
    d1, d2 = jnp.asarray(G2.from_ref(P1)), jnp.asarray(G2.from_ref(P2))
    assert G2.to_ref(G2.add(d1, d2)) == refimpl.g2_add(P1, P2)
    assert G2.to_ref(G2.double(d1)) == refimpl.g2_add(P1, P1)
    # doubling path through add
    assert G2.to_ref(G2.add(d1, d1)) == refimpl.g2_add(P1, P1)
    # inverse points -> infinity
    assert G2.to_ref(G2.add(d1, G2.neg(d1))) is None


def test_g2_scalar_mul_matches_oracle():
    k = int.from_bytes(RNG.bytes(31), "little")
    dG = jnp.asarray(G2.G2_GEN)
    got = G2.to_ref(G2.scalar_mul(dG, jnp.asarray(F.from_int(k % params.N))))
    assert got == refimpl.g2_mul(refimpl.G2, k)


def _pair_dev(p1, q2):
    """Host points -> device pairing -> oracle representation."""
    xp_m = jnp.asarray(F.from_int(p1[0] * params.R % params.P))
    yp_m = jnp.asarray(F.from_int(p1[1] * params.R % params.P))
    xq = jnp.asarray(F2.from_ref(q2[0]))
    yq = jnp.asarray(F2.from_ref(q2[1]))
    return F12.to_ref(PAIR.pair((xp_m, yp_m), (xq, yq)))


def test_pairing_matches_oracle():
    got = _pair_dev(refimpl.G1, refimpl.G2)
    want = refimpl.pair(refimpl.G1, refimpl.G2)
    assert got == want


def test_pairing_bilinear_on_device():
    a, b = 7, 13
    Pa = refimpl.g1_mul(refimpl.G1, a)
    Qb = refimpl.g2_mul(refimpl.G2, b)
    lhs = _pair_dev(Pa, Qb)
    base = refimpl.pair(refimpl.G1, refimpl.G2)
    rhs = refimpl.fp12_pow(base, a * b)
    assert lhs == rhs


def test_gt_membership_gate():
    """GΦ12 membership: pairing outputs pass; a GT element multiplied by a
    non-cyclotomic unit fails — the gate that keeps forged wire elements
    away from the cyclotomic-squaring pow chains (batching.gt_membership_ok).
    """
    import numpy as np

    from drynx_tpu.crypto import batching as B
    from drynx_tpu.crypto import fp12 as F12

    f = jnp.asarray(F12.from_ref(refimpl.pair(refimpl.G1, refimpl.G2)))
    assert B.gt_membership_ok(f[None])
    # conj6(f) = f^-1 for members: also a member
    assert B.gt_membership_ok(F12.conj6(f)[None])
    # a unit outside GΦ12: the Fp12 element 1 + w (invertible, generic)
    g = [tuple(c) for c in refimpl.FP12_ONE]
    g[1] = (1, 0)
    bad = jnp.asarray(F12.from_ref(g))
    assert not B.gt_membership_ok(bad[None])
    # mixed batch: one bad element fails the whole batch
    both = jnp.stack([f, bad])
    assert not B.gt_membership_ok(both)


def test_gt_order_gate():
    """Order-n gate (batching.gt_order_ok): honest pairing outputs pass; a
    cofactor root of unity (order 13 — 13 divides Φ12(p)/n for this curve)
    passes the GΦ12 membership gate but MUST fail the order gate, since it
    is exactly the element a commit-first RLC forger would inject."""
    import numpy as np

    from drynx_tpu.crypto import batching as B
    from drynx_tpu.crypto import fp12 as F12
    from drynx_tpu.crypto import params

    f = jnp.asarray(F12.from_ref(refimpl.pair(refimpl.G1, refimpl.G2)))
    assert B.gt_order_ok(f[None])

    # 13 divides Φ12(p)/n for this curve — asserted inside the helper
    eps = refimpl.gphi12_cofactor_element(13)
    eps_d = jnp.asarray(F12.from_ref(eps))
    assert B.gt_membership_ok(eps_d[None])     # inside GΦ12 ...
    assert not B.gt_order_ok(eps_d[None])      # ... outside order-n GT
    # a tampered honest element and a mixed batch also fail
    bad = jnp.asarray(F12.from_ref(refimpl.fp12_mul(
        refimpl.pair(refimpl.G1, refimpl.G2), eps)))
    assert not B.gt_order_ok(bad[None])
    assert not B.gt_order_ok(jnp.stack([f, bad]))


def test_host_oracle_final_exp_fast_parity():
    """host_oracle.final_exp_fast (easy + Olivos hard part on ints) must be
    bit-identical to refimpl.final_exp (the naive full exponentiation) on
    Miller outputs — it backs every CPU-path pairing in the proof layer."""
    from drynx_tpu.crypto import host_oracle as ho

    m = refimpl.ate_miller_loop(refimpl.g1_mul(refimpl.G1, 7), refimpl.G2)
    assert ho.final_exp_fast(m) == refimpl.final_exp(m)
    # and therefore the full host pairing equals refimpl.pair
    import numpy as np

    from drynx_tpu.crypto import curve as Cv
    from drynx_tpu.crypto import g2 as G2m
    from drynx_tpu.crypto import batching as B

    p = Cv.from_ref(refimpl.g1_mul(refimpl.G1, 7))[None]
    q = jnp.asarray(G2m.from_ref(refimpl.G2))[None]
    px, py, _ = B.g1_normalize(p)
    qx, qy, _ = B.g2_normalize(q)
    got = ho.pair_host(np.asarray(px), np.asarray(py), np.asarray(qx),
                       np.asarray(qy))
    want = refimpl.pair(refimpl.g1_mul(refimpl.G1, 7), refimpl.G2)
    assert F12.to_ref(jnp.asarray(got[0])) == want
