"""Pallas kernel parity vs the jnp curve layer (interpreter mode on CPU).

On TPU these kernels are the dispatch target of curve.scalar_mul /
elgamal.fixed_base_mul (crypto/pallas_ops.py); here they run through the
Pallas interpreter so the kernel code paths are covered by the CPU suite."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# The full ladder kernels take many minutes to compile through the
# interpreter on CPU; they are validated on real TPU by
# scripts/pallas_probe.py. Opt in with DRYNX_PALLAS_INTERPRET_TESTS=1.
heavy = pytest.mark.skipif(
    os.environ.get("DRYNX_PALLAS_INTERPRET_TESTS", "0") != "1",
    reason="ladder-kernel interpret compile is minutes-slow on CPU; "
           "covered on hardware by scripts/pallas_probe.py")

from drynx_tpu.crypto import curve as C
from drynx_tpu.crypto import elgamal as eg
from drynx_tpu.crypto import field as F
from drynx_tpu.crypto import pallas_ops as po
from drynx_tpu.crypto import params, refimpl

RNG = np.random.default_rng(17)


@pytest.fixture(autouse=True)
def interpret_mode(monkeypatch):
    monkeypatch.setattr(po, "INTERPRET", True)


def _rand_points(n):
    ks = [int.from_bytes(RNG.bytes(32), "little") % params.N
          for _ in range(n)]
    pts = [refimpl.g1_mul(refimpl.G1, k) for k in ks]
    return jnp.asarray(C.from_ref_batch(pts)), pts


def _rand_scalars(n):
    ss = [int.from_bytes(RNG.bytes(32), "little") % params.N
          for _ in range(n)]
    return jnp.asarray(F.from_int(ss)), ss


def _assert_points_equal(a, b):
    ax, ay, ai = C.normalize(a)
    bx, by, bi = C.normalize(b)
    assert bool(jnp.all(ai == bi))
    fin = ~np.asarray(ai)
    assert bool(np.all(np.asarray(ax)[fin] == np.asarray(bx)[fin]))
    assert bool(np.all(np.asarray(ay)[fin] == np.asarray(by)[fin]))


@heavy
def test_scalar_mul_kernel_matches_jnp():
    n = 4
    p, _ = _rand_points(n)
    k, _ = _rand_scalars(n)
    k = k.at[0].set(0)  # edge: zero scalar -> infinity
    out_pallas = po.scalar_mul_flat(p, k)
    out_jnp = C._scalar_mul_jnp(p, k)
    _assert_points_equal(out_pallas, out_jnp)


@heavy
def test_fixed_base_kernel_matches_jnp():
    n = 5
    k, ss = _rand_scalars(n)
    out_pallas = po.fixed_base_mul_flat(eg.BASE_TABLE.table, k)
    out_jnp = eg._fixed_base_mul_jnp(eg.BASE_TABLE.table, k)
    _assert_points_equal(out_pallas, out_jnp)
    assert C.to_ref(out_pallas[1]) == refimpl.g1_mul(refimpl.G1, ss[1])


@heavy
def test_fixed_base_ladder_small_always_on():
    """Formerly always-on slice of the ladder kernel (n_windows=2): measured
    in round 4, even this truncated interpret compile runs tens of minutes
    on this box under jax 0.8, so it joins the opt-in interpret tier — the
    kernels are validated on hardware (scripts/pallas_probe.py) and the
    digit/table/padd logic is oracle-tested at the jnp layer."""
    ss = [0, 1, 200]  # infinity edge + generator + 2-digit scalar
    k = jnp.asarray(F.from_int(ss))
    out_pallas = po.fixed_base_mul_flat(eg.BASE_TABLE.table, k, n_windows=2)
    out_jnp = eg._fixed_base_mul_jnp(eg.BASE_TABLE.table, k, n_windows=2)
    _assert_points_equal(out_pallas, out_jnp)
    assert C.to_ref(out_pallas[2]) == refimpl.g1_mul(refimpl.G1, 200)


@heavy
def test_point_add_and_reduce_kernels():
    n = 3
    p, _ = _rand_points(n)
    q, _ = _rand_points(n)
    _assert_points_equal(po.point_add_flat(p, q), C.add(p, q))

    stack = jnp.stack([p, q, C.neg(p)])       # (3, n, 3, 16)
    want = C.add(C.add(p, q), C.neg(p))       # == q
    _assert_points_equal(po.point_reduce_flat(stack), want)
