"""Pallas pairing kernels vs the jnp pairing + pure-Python oracle
(interpret mode on CPU; the real-chip path is exercised by
scripts/bench_proofs.py and the TPU benches — all kernels here were
verified against the oracle on the actual v5e chip during development).

Covers: Fp12 mul/inv/pow kernels, the ate Miller kernel (up to the free
Fp2 line scales — compared after final exponentiation), and the full
reduced pairing against refimpl.pair.
"""
import os

import numpy as np
import jax.numpy as jnp
import pytest

from drynx_tpu.crypto import fp2 as F2
from drynx_tpu.crypto import fp12 as F12
from drynx_tpu.crypto import field as F
from drynx_tpu.crypto import pallas_ops as po
from drynx_tpu.crypto import pallas_pairing as pp
from drynx_tpu.crypto import params, refimpl

# Interpreting the pairing kernels on CPU compiles for >40 min on this
# one-core box (same reason the ladder kernels are opt-in,
# tests/test_pallas_kernels.py:16); they are validated on hardware.
pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        os.environ.get("DRYNX_PALLAS_INTERPRET_TESTS", "0") != "1",
        reason="pairing-kernel interpret compile is ~1h on CPU; verified "
               "on TPU by scripts/bench_proofs.py"),
]

RNG = np.random.default_rng(23)


@pytest.fixture(autouse=True)
def interpret_mode(monkeypatch):
    # INTERPRET is threaded through as a static arg / per-mode jit key
    # (batching._trace_mode), so interpret-mode traces cannot leak into
    # later tests — no cache-clearing teardown needed.
    monkeypatch.setattr(po, "INTERPRET", True)
    monkeypatch.setattr(pp, "INTERPRET", True)


def rfp():
    return int.from_bytes(RNG.bytes(40), "little") % params.P


def rf12():
    return tuple((rfp(), rfp()) for _ in range(6))


def test_f12_mul_inv_pow_kernels():
    a, b = rf12(), rf12()
    da = jnp.asarray(F12.from_ref(a))[None]
    db = jnp.asarray(F12.from_ref(b))[None]
    assert F12.to_ref(pp.f12_mul_flat(da, db)[0]) == refimpl.fp12_mul(a, b)
    inv = pp.f12_inv_flat(da)
    assert refimpl.fp12_mul(F12.to_ref(inv[0]), a) == refimpl.FP12_ONE

    e = 0xABCDEF123456
    k = jnp.asarray(F.from_int(e))[None]
    got = pp.f12_pow_flat(da, k, n_bits=48)
    assert F12.to_ref(got[0]) == refimpl.fp12_pow(a, e)


def test_pair_kernel_matches_oracle():
    P1 = refimpl.g1_mul(refimpl.G1, 5)
    Q1 = refimpl.g2_mul(refimpl.G2, 9)
    xp = jnp.asarray(F.from_int(P1[0] * params.R % params.P))[None]
    yp = jnp.asarray(F.from_int(P1[1] * params.R % params.P))[None]
    xq = jnp.asarray(F2.from_ref(Q1[0]))[None]
    yq = jnp.asarray(F2.from_ref(Q1[1]))[None]

    want = refimpl.pair(P1, Q1)
    # Miller value differs from the jnp one only by free Fp2 line scales:
    # compare after the final exponentiation
    gm = pp.miller_flat(xp, yp, xq, yq)
    assert refimpl.final_exp(F12.to_ref(gm[0])) == want

    got = pp.pair_flat(xp, yp, xq, yq)
    assert F12.to_ref(got[0]) == want


def test_wpow_kernel_matches_oracle():
    a = rf12()
    da = jnp.asarray(F12.from_ref(a))[None]
    e = 0xBEEF1234
    k = jnp.asarray(F.from_int(e))[None]
    got = pp.f12_wpow_flat(da, k, n_bits=32)
    assert F12.to_ref(got[0]) == refimpl.fp12_pow(a, e)


def test_mulreduce8_and_fixed_base_pow():
    vals = [rf12() for _ in range(8)]
    g = jnp.asarray(np.stack([F12.from_ref(v) for v in vals]))[None]
    got = pp.f12_mulreduce8_flat(g)
    want = vals[0]
    for v in vals[1:]:
        want = refimpl.fp12_mul(want, v)
    assert F12.to_ref(got[0]) == want

    from drynx_tpu.proofs import range_proof as rp
    tab = rp.gt_base_table()
    gtb = refimpl.pair(refimpl.G1, refimpl.G2)
    e = int.from_bytes(RNG.bytes(20), "little")
    k = jnp.asarray(F.from_int(e))[None]
    got = pp.gt_pow_fixed(tab, k)
    assert F12.to_ref(got[0]) == refimpl.fp12_pow(gtb, e)


def test_csqr_kernel_matches_generic_square_on_cyclotomic():
    """Granger-Scott cyclotomic squaring == generic squaring on GΦ12
    elements (pairing outputs); also via the wpow cyc=True chain."""
    f = refimpl.pair(refimpl.G1, refimpl.G2)
    df = jnp.asarray(F12.from_ref(f))[None]
    got = pp.f12_csqr_flat(df)
    assert F12.to_ref(got[0]) == refimpl.fp12_mul(f, f)

    e = 0xDEADBEEFCAFE
    k = jnp.asarray(F.from_int(e))[None]
    got = pp.f12_wpow_flat(df, k, n_bits=48, cyc=True)
    assert F12.to_ref(got[0]) == refimpl.fp12_pow(f, e)


def test_scalar_mul_kernel_short_windows():
    """n_windows=16 ladder == full ladder for 62-bit scalars (G1)."""
    from drynx_tpu.crypto import curve as C

    k_int = int.from_bytes(RNG.bytes(7), "little")  # < 2^56
    pt = jnp.asarray(C.from_ref(refimpl.G1))[None]
    k = jnp.asarray(F.from_int(k_int))[None]
    full = po.scalar_mul_flat(pt, k)
    short = po.scalar_mul_flat(pt, k, n_windows=16)
    assert bool(np.all(np.asarray(C.eq(full, short))))


def test_gt_pow_fixed_multi_matches_oracle():
    """Creation's multi-base fixed-window pow: gather + mulreduce8 ==
    fp12_pow on the selected base (interpret mode)."""
    from drynx_tpu.crypto import host_oracle as ho

    bases = [refimpl.pair(refimpl.g1_mul(refimpl.G1, i + 2), refimpl.G2)
             for i in range(3)]
    NB = len(bases)
    T = np.empty((NB, 64, 16, 6, 2, 16), np.uint32)
    for b, cur0 in enumerate(bases):
        cur = cur0
        for w in range(64):
            row = refimpl.FP12_ONE
            T[b, w, 0] = ho._fp12_from_ref(row)
            for j in range(1, 16):
                row = refimpl.fp12_mul(row, cur)
                T[b, w, j] = ho._fp12_from_ref(row)
            for _ in range(4):
                cur = refimpl.fp12_sq(cur)
    es = [0x123456789ABCDEF0, 7, int.from_bytes(RNG.bytes(30), "little")]
    idx = jnp.asarray([2, 0, 1], dtype=jnp.int32)
    k = jnp.asarray(np.stack([np.asarray(F.from_int(e % params.N))
                              for e in es]))
    got = pp.gt_pow_fixed_multi(jnp.asarray(T), idx, k)
    for i, e in enumerate(es):
        want = refimpl.fp12_pow(bases[int(idx[i])], e % params.N)
        assert F12.to_ref(got[i]) == want, i
