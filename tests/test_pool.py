"""Crypto-pool integrity (drynx_tpu/pool): single consumption across
threads AND process restarts, crash recovery, decrypt-correctness of
pooled DRO, persistent sig tables, and the server refill lane.

The single-consumption property is load-bearing PRIVACY, not hygiene:
reusing one DRO re-randomization mask across two surveys lets a proof
observer cancel the masks and recover both secret permutations — so a
slab handed out twice must raise, whatever the interleaving.
"""
import os
import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from drynx_tpu import pool as pool_mod  # noqa: E402
from drynx_tpu.crypto import elgamal as eg  # noqa: E402
from drynx_tpu.parallel import dro  # noqa: E402
from drynx_tpu.pool import replenish  # noqa: E402


@pytest.fixture(autouse=True)
def _no_global_pool():
    """Each test controls its own active pool; never leak one."""
    pool_mod.activate(None)
    yield
    pool_mod.activate(None)


@pytest.fixture
def keypair():
    rng = np.random.default_rng(42)
    x, pub = eg.keygen(rng)
    return x, pub, eg.pub_table(pub)


def _fill(pool, tbl, n_slabs, seed=0):
    k = jax.random.PRNGKey(seed)
    sids = []
    for _ in range(n_slabs):
        k, s = jax.random.split(k)
        sids.append(replenish.refill_slab(pool, s, tbl.table))
    return sids


# ---------------------------------------------------------------------------
# store mechanics
# ---------------------------------------------------------------------------

def test_deposit_consume_roundtrip(tmp_path, keypair):
    _, _, tbl = keypair
    pool = pool_mod.CryptoPool(str(tmp_path), slab_elems=8)
    dig = pool_mod.key_digest(tbl.table)
    _fill(pool, tbl, 3)
    assert pool.dro_balance(dig) == 24
    z, r = pool.consume_dro(dig, 10)
    # exact trim; remaining tail of the second slab is discarded with it
    assert z.shape == (10, 2, 3, 16) and r.shape == (10, 16)
    assert pool.dro_balance(dig) == 8
    assert pool.counters["consumed"] == 2
    # short pool: try_* declines, consume_* raises typed
    assert pool.try_consume_dro(dig, 9) is None
    with pytest.raises(pool_mod.InsufficientBalance):
        pool.consume_dro(dig, 9)


def test_consume_under_wrong_key_digest_finds_nothing(tmp_path, keypair):
    """Slabs are content-addressed by the collective-key table: a pool
    warm for key A has zero balance for key B (serving cross-key slabs
    would silently corrupt the re-randomization)."""
    _, _, tbl = keypair
    pool = pool_mod.CryptoPool(str(tmp_path), slab_elems=8)
    _fill(pool, tbl, 1)
    x2, pub2 = eg.keygen(np.random.default_rng(43))
    other = pool_mod.key_digest(eg.pub_table(pub2).table)
    assert pool.dro_balance(other) == 0
    assert pool.try_consume_dro(other, 1) is None


def test_double_consumption_across_threads(tmp_path, keypair):
    _, _, tbl = keypair
    pool = pool_mod.CryptoPool(str(tmp_path), slab_elems=4)
    dig = pool_mod.key_digest(tbl.table)
    (sid,) = _fill(pool, tbl, 1)

    wins, raises = [], []
    barrier = threading.Barrier(8)

    def claim():
        barrier.wait()
        try:
            pool.consume_slab(dig, sid)
            wins.append(1)
        except pool_mod.DoubleConsumption:
            raises.append(1)

    ts = [threading.Thread(target=claim) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(wins) == 1 and len(raises) == 7


def test_double_consumption_across_restart(tmp_path, keypair):
    _, _, tbl = keypair
    pool = pool_mod.CryptoPool(str(tmp_path), slab_elems=4)
    dig = pool_mod.key_digest(tbl.table)
    (sid,) = _fill(pool, tbl, 1)
    pool.consume_slab(dig, sid)
    # "restart": a fresh store over the same root replays the ledger
    pool2 = pool_mod.CryptoPool(str(tmp_path), slab_elems=4)
    with pytest.raises(pool_mod.DoubleConsumption):
        pool2.consume_slab(dig, sid)
    assert pool2.dro_balance(dig) == 0


def test_crash_recovery_discards_partials_and_claimed(tmp_path, keypair):
    """A writer killed mid-segment leaves a *.tmp; a consumer killed
    between tombstone and release leaves a *.claimed. Reopen discards
    both — the claimed slab's randomness was tombstoned, so it must
    never re-enter the pool — and the ledger stays consistent."""
    _, _, tbl = keypair
    pool = pool_mod.CryptoPool(str(tmp_path), slab_elems=4)
    dig = pool_mod.key_digest(tbl.table)
    _fill(pool, tbl, 2)
    live = pool._live_slabs(dig)
    assert len(live) == 2
    # crashed writer: partial segment under the atomic-write tmp name
    slab_dir = os.path.dirname(live[0])
    with open(os.path.join(slab_dir, "slab_deadbeef.npz.tmp"), "wb") as f:
        f.write(b"partial garbage")
    # crashed consumer: claimed (tombstoned) but never unlinked
    os.rename(live[0], live[0] + ".claimed")
    claimed_sid = os.path.basename(live[0])[len("slab_"):-len(".npz")]

    pool2 = pool_mod.CryptoPool(str(tmp_path), slab_elems=4)
    assert pool2.dro_balance(dig) == 4          # only the intact slab
    assert pool2.counters["recovered"] == 1
    assert not any(p.endswith((".tmp", ".claimed"))
                   for p in _walk(str(tmp_path)))
    # the recovered slab is tombstoned forever, even after ANOTHER reopen
    pool3 = pool_mod.CryptoPool(str(tmp_path), slab_elems=4)
    with pytest.raises(pool_mod.DoubleConsumption):
        pool3.consume_slab(dig, claimed_sid)


def _walk(root):
    for d, _, fs in os.walk(root):
        for f in fs:
            yield os.path.join(d, f)


def test_ledger_survives_torn_tail(tmp_path, keypair):
    """A crash mid-append leaves a torn final JSON line; replay must drop
    it without losing the earlier events."""
    _, _, tbl = keypair
    pool = pool_mod.CryptoPool(str(tmp_path), slab_elems=4)
    dig = pool_mod.key_digest(tbl.table)
    (sid,) = _fill(pool, tbl, 1)
    pool.consume_slab(dig, sid)
    with open(pool._ledger_path, "a", encoding="utf-8") as f:
        f.write('{"ev": "cons')        # torn
    pool2 = pool_mod.CryptoPool(str(tmp_path), slab_elems=4)
    with pytest.raises(pool_mod.DoubleConsumption):
        pool2.consume_slab(dig, sid)


# ---------------------------------------------------------------------------
# DRO correctness with pooled randomness
# ---------------------------------------------------------------------------

def test_pooled_shuffle_decrypts_like_fresh(tmp_path, keypair):
    """Pooled and fresh-randomness DRO produce DIFFERENT ciphertexts
    (different blinding scalars) but the SAME permutation (drawn from the
    pipeline key, independent of precomp) and the same plaintexts —
    zero-encryptions add zero whatever their r."""
    x, pub, tbl = keypair
    S = 8
    noise = np.array([0, 1, -1, 2, -2, 0, 1, -1], dtype=np.int64)
    k_enc, k_sh, k_pool = jax.random.split(jax.random.PRNGKey(3), 3)
    cts = dro.encrypt_noise(k_enc, tbl, noise)

    pool = pool_mod.CryptoPool(str(tmp_path), slab_elems=S)
    replenish.refill_slab(pool, k_pool, tbl.table)
    got = pool.consume_dro(pool_mod.key_digest(tbl.table), S)
    pooled = (jnp.asarray(got[0]), jnp.asarray(got[1]))

    out_pool, perm_pool, _ = dro.shuffle_rerandomize(
        k_sh, cts, tbl.table, precomp=pooled)
    out_fresh, perm_fresh, _ = dro.shuffle_rerandomize(
        k_sh, cts, tbl.table)
    assert np.array_equal(np.asarray(perm_pool), np.asarray(perm_fresh))

    dl = eg.DecryptionTable(limit=8)
    vp, fp = eg.decrypt_ints(out_pool, x, dl)
    vf, ff = eg.decrypt_ints(out_fresh, x, dl)
    assert bool(np.all(np.asarray(fp))) and bool(np.all(np.asarray(ff)))
    assert np.array_equal(np.asarray(vp), np.asarray(vf))
    assert np.array_equal(np.sort(np.asarray(vp)), np.sort(noise))


def test_dro_pipeline_pool_skips_precompute(tmp_path, keypair):
    x, _, tbl = keypair
    S, n_servers = 8, 2
    pool = pool_mod.CryptoPool(str(tmp_path), slab_elems=S)
    replenish.refill_to(pool, jax.random.PRNGKey(9), tbl.table,
                        S * n_servers)
    before = dro.PRECOMPUTE_CALLS
    cts, noise = dro.dro_pipeline(jax.random.PRNGKey(4), tbl, S, 0.0, 2.0,
                                  1.0, scale=1.0, limit=4.0,
                                  n_servers=n_servers, pool=pool)
    assert dro.PRECOMPUTE_CALLS == before      # warm pool: zero builds
    assert pool.counters["elements_consumed"] == S * n_servers
    dl = eg.DecryptionTable(limit=8)
    vals, found = eg.decrypt_ints(cts, x, dl)
    assert bool(np.all(np.asarray(found)))
    assert np.array_equal(np.sort(np.asarray(vals)), np.sort(noise))


# ---------------------------------------------------------------------------
# persistent sig-table store (restart skips builder invocations)
# ---------------------------------------------------------------------------

def test_sig_store_restart_skips_builds(tmp_path):
    from drynx_tpu.proofs import range_proof as rproof

    pool = pool_mod.CryptoPool(str(tmp_path))
    pool_mod.activate(pool)
    sigs = [rproof.init_range_sig(2, np.random.default_rng(7))]

    rproof.prewarm_sig_tables(sigs, pow_tables=True)
    gt0 = np.asarray(rproof.sig_gt_table(sigs))
    pow0 = np.asarray(rproof.sig_gt_pow_tables(sigs))
    builds = dict(rproof.SIG_BUILD_COUNTS)
    assert builds["gt_table"] >= 1 and builds["pow_table"] >= 1

    # simulated restart: same signatures rebuilt from the same rng seed,
    # every in-process cache dropped — only the disk store remains
    sigs2 = [rproof.init_range_sig(2, np.random.default_rng(7))]
    assert np.array_equal(sigs2[0].A, sigs[0].A)
    rproof._GT_TABLE_CACHE.clear()
    rproof._GT_POW_TABLE_CACHE.clear()
    rproof._GT_POW_TABLE_DEV.clear()

    rproof.prewarm_sig_tables(sigs2, pow_tables=True)
    gt1 = np.asarray(rproof.sig_gt_table(sigs2))
    pow1 = np.asarray(rproof.sig_gt_pow_tables(sigs2))
    assert dict(rproof.SIG_BUILD_COUNTS) == builds   # zero new builds
    assert np.array_equal(gt0, gt1)
    assert np.array_equal(pow0, pow1)


def test_fb_table_restart_skips_host_build(tmp_path, keypair):
    """Fixed-base tables persist through the fb tenant: a fresh store
    instance on the same root serves the table without paying the host
    EC ladder build (FB_BUILD_COUNT flat), bytes identical."""
    _, pub, _ = keypair
    pool_mod.activate(pool_mod.CryptoPool(str(tmp_path)))
    t0 = eg.pub_table(pub)
    builds = eg.FB_BUILD_COUNT
    pool_mod.activate(pool_mod.CryptoPool(str(tmp_path)))   # restart
    t1 = eg.pub_table(pub)
    assert eg.FB_BUILD_COUNT == builds
    assert np.array_equal(np.asarray(t0.table), np.asarray(t1.table))


# ---------------------------------------------------------------------------
# service + server integration
# ---------------------------------------------------------------------------

def _diffp():
    from drynx_tpu.service.query import DiffPParams

    return DiffPParams(noise_list_size=8, lap_mean=0.0, lap_scale=2.0,
                       quanta=1.0, scale=1.0, limit=4.0)


def test_survey_consumes_pool_and_restart_skips_precompute(tmp_path):
    """ISSUE-9 acceptance: a fresh process with a warm pool skips ALL
    pool precompute — builder invocations stay flat across a simulated
    restart (fresh LocalCluster, same roster seed, same disk pool)."""
    from drynx_tpu.service.service import LocalCluster

    pool = pool_mod.CryptoPool(str(tmp_path), slab_elems=8)
    cl1 = LocalCluster(n_cns=2, n_dps=2, n_vns=0, seed=19,
                       dlog_limit=2000, pool=pool)
    replenish.refill_to(pool, jax.random.PRNGKey(11),
                        cl1.coll_tbl.table, 8 * 2 * 2)
    dig = pool_mod.key_digest(cl1.coll_tbl.table)

    def run(cl):
        for dp in cl.dps.values():
            dp.data = np.arange(4, dtype=np.int64)
        sq = cl.generate_survey_query("sum", query_min=0, query_max=5,
                                      diffp=_diffp())
        return cl.run_survey(sq)

    before = dro.PRECOMPUTE_CALLS
    res = run(cl1)
    assert dro.PRECOMPUTE_CALLS == before        # pooled, no fresh builds
    assert abs(res.result - 12) <= 4
    assert pool.counters["elements_consumed"] == 8 * 2

    # restart: fresh cluster + fresh store over the same root
    pool2 = pool_mod.CryptoPool(str(tmp_path), slab_elems=8)
    cl2 = LocalCluster(n_cns=2, n_dps=2, n_vns=0, seed=19,
                       dlog_limit=2000, pool=pool2)
    assert pool2.dro_balance(dig) == 16
    before = dro.PRECOMPUTE_CALLS
    res2 = run(cl2)
    assert dro.PRECOMPUTE_CALLS == before
    assert abs(res2.result - 12) <= 4


def test_server_refill_lane(tmp_path):
    """An empty pool routes a diffp survey to the refill lane; the drain
    thread deposits slabs cooperatively until the balance covers the
    noise need, then the survey runs pooled (zero fresh precompute)."""
    from drynx_tpu.server import SurveyServer
    from drynx_tpu.service.service import LocalCluster

    pool = pool_mod.CryptoPool(str(tmp_path), slab_elems=8)
    cl = LocalCluster(n_cns=2, n_dps=2, n_vns=0, seed=23,
                      dlog_limit=2000, pool=pool)
    for dp in cl.dps.values():
        dp.data = np.arange(4, dtype=np.int64)
    srv = SurveyServer(cl, pipeline=False)
    sq = cl.generate_survey_query("sum", query_min=0, query_max=5,
                                  diffp=_diffp(), survey_id="s_refill")
    a = srv.submit(sq)
    assert a.lane == "refill" and a.dro_need == 16
    before = dro.PRECOMPUTE_CALLS
    results = srv.drain()
    res = results["s_refill"]
    assert not isinstance(res, Exception), res
    assert abs(res.result - 12) <= 4
    # refill deposited exactly the need (2 slabs of 8), all consumed
    assert srv.refill_slabs == 2
    # the refill lane paid the precompute (2 slabs), the survey itself
    # paid none beyond it
    assert dro.PRECOMPUTE_CALLS == before + 2
    assert pool.counters["elements_consumed"] == 16
    # warm pool now: a second identical survey goes straight to fast
    replenish.refill_to(pool, jax.random.PRNGKey(29),
                        cl.coll_tbl.table, 16)
    sq2 = cl.generate_survey_query("sum", query_min=0, query_max=5,
                                   diffp=_diffp(), survey_id="s_fast")
    assert srv.submit(sq2).lane == "fast"
    results = srv.drain()
    assert not isinstance(results["s_fast"], Exception)
