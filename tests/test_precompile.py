"""Compilecache registry structure: the declared program set must cover
every trace entry a proofs-on survey dispatches (BUCKETED_OPS + the raw
Pallas flat kernels + the fused service jits). Trace-free by default —
only the two driver smoke tests lower anything, and only the cheapest
scalar-field programs."""
import sys

import pytest

from drynx_tpu import compilecache as cc
from drynx_tpu.compilecache.stats import CompileStats


@pytest.fixture(scope="module")
def registry():
    return cc.build_registry(cc.BENCH)


def test_registry_covers_every_bucketed_op(registry):
    """Every named bucketed op (including the lazy range-proof wrappers
    force-built by aot_register_bucketed) has at least one registered
    program — a new `name=`d bucketed() call site without a registry
    entry fails here."""
    from drynx_tpu.crypto import batching as B

    covered = {s.op for s in registry if s.kind == "bucketed"}
    missing = set(B.BUCKETED_OPS) - covered
    assert not missing, (
        f"BUCKETED_OPS entries without a compilecache program: {missing} "
        f"— add them to registry._B_SCHEMAS")
    # the Pallas-only lazy wrappers are registered even when the current
    # backend never builds them (they are skipped, not absent)
    assert {"gt_pow_fixed_multi", "gt_pow_gtb"} <= covered


def test_registry_covers_pallas_and_fused_families(registry):
    ops = {(s.kind, s.op) for s in registry}
    for op in ("miller_flat", "f12_wpow_flat", "f12_mulreduce8_flat"):
        assert ("pallas", op) in ops
    for op in ("enc", "agg", "ks", "dec"):
        assert ("fused", op) in ops


def test_registry_names_unique_and_thunks_wellformed(registry):
    names = [s.name for s in registry]
    assert len(names) == len(set(names))
    for s in registry:
        assert callable(s.lower) and callable(s.dispatched), s.name
        assert s.call is None or callable(s.call), s.name
        assert s.kind in ("bucketed", "pallas", "fused", "pool",
                          "wire", "pane"), s.name


def test_registry_scales_with_profile():
    small = cc.Profile(n_cns=2, n_dps=2, n_values=2, u=4, l=2,
                       dlog_limit=100)
    specs = cc.build_registry(small)
    # smaller survey -> smaller buckets -> at least as few programs, and
    # every bucketed name stays within the wrapper's max_bucket cap
    for s in specs:
        if s.kind == "bucketed":
            bucket = int(s.name.rsplit("@", 1)[1])
            assert bucket <= 2048


def test_registry_sharded_program_set():
    """Profile.n_shards > 1 must add the proof-plane per-shard programs
    (the smaller buckets each mesh device dispatches) on BOTH phases —
    creation and verification — and must only ever ADD programs: the
    single-shard registry is a strict subset, so sharding can never
    silently drop AOT coverage of the fallback path."""
    base = cc.BENCH
    sharded = cc.build_registry(
        cc.Profile(n_cns=base.n_cns, n_dps=base.n_dps,
                   n_values=base.n_values, u=base.u, l=base.l,
                   dlog_limit=base.dlog_limit, n_shards=8))
    flat = cc.build_registry(base)
    flat_names = {s.name for s in flat}
    sharded_names = {s.name for s in sharded}
    assert flat_names <= sharded_names
    extra = [s for s in sharded if s.name not in flat_names]
    assert extra, "n_shards=8 must add per-shard programs"
    phases = {s.phase for s in extra}
    assert phases <= {"RangeProofVerifyShard", "RangeProofCreateShard"}
    assert "RangeProofVerifyShard" in phases
    assert "RangeProofCreateShard" in phases
    # the verify shard's pairing programs at the per-shard bucket
    ops = {s.op for s in extra}
    assert {"miller", "gt_pow64"} <= ops
    # per-shard buckets are smaller than the full flat batch
    for s in extra:
        if s.kind == "bucketed":
            assert int(s.name.rsplit("@", 1)[1]) <= 2048


def test_registry_bucket_grid_program_set():
    """Profile.n_buckets above the tile threshold must add the bucket-tile
    programs (tile-derived creation shards + fused enc at tile slab
    widths) and must only ever ADD: the plain registry at the same grid
    shape is a strict subset, mirroring the n_shards / n_queue
    contracts."""
    grid = cc.Profile(n_values=65536, u=2, l=1, n_buckets=65536)
    plain = cc.Profile(n_values=65536, u=2, l=1)
    grid_names = {s.name for s in cc.build_registry(grid)}
    plain_names = {s.name for s in cc.build_registry(plain)}
    assert plain_names <= grid_names
    extra = [s for s in cc.build_registry(grid)
             if s.name not in plain_names]
    assert extra, "n_buckets=65536 must add bucket-tile programs"
    phases = {s.phase for s in extra}
    assert phases <= {"RangeProofCreateTile", "DataCollectionTile"}
    assert "RangeProofCreateTile" in phases
    # the chunked-encrypt slab program at the tile width
    assert any(s.name.startswith("fused:enc@") for s in extra)


def test_registry_bucket_grid_below_threshold_is_identity():
    """A grid at or below the tile threshold never tiles, so n_buckets
    must add nothing — the existing program set is exactly preserved."""
    with_b = cc.build_registry(
        cc.Profile(n_values=256, u=2, l=1, n_buckets=256))
    without = cc.build_registry(cc.Profile(n_values=256, u=2, l=1))
    assert {s.name for s in with_b} == {s.name for s in without}


def test_registry_n_buckets_zero_is_identity():
    base = cc.BENCH
    zero = cc.build_registry(dataclasses_replace(base, n_buckets=0))
    assert {s.name for s in zero} == {s.name
                                      for s in cc.build_registry(base)}


def dataclasses_replace(p, **kw):
    import dataclasses

    return dataclasses.replace(p, **kw)


def test_registry_n_shards_one_is_identity():
    base = cc.BENCH
    one = cc.build_registry(
        cc.Profile(n_cns=base.n_cns, n_dps=base.n_dps,
                   n_values=base.n_values, u=base.u, l=base.l,
                   dlog_limit=base.dlog_limit, n_shards=1))
    assert {s.name for s in one} == {s.name for s in cc.build_registry(base)}


def test_driver_lower_smoke_cheap_program():
    """spec.lower() on the cheapest scalar-field program returns an AOT
    Lowered (compile()-able); the driver records it as 'lowered'."""
    stats = CompileStats()
    specs = [s for s in cc.build_registry(cc.BENCH)
             if s.op in ("fn_add", "int_to_scalar") and s.dispatched()]
    assert specs, "scalar-field programs must dispatch on every backend"
    lowered = specs[0].lower()
    assert hasattr(lowered, "compile")
    stats.record(specs[0].name, "lowered", lower_s=0.1)
    assert stats.count("lowered") == 1


def test_stats_headline_keys_and_totals():
    stats = CompileStats()
    stats.record("a", "compiled", lower_s=1.0, compile_s=2.0, cache="miss")
    stats.record("b", "executed", lower_s=0.5, cache="hit")
    stats.record("c", "skipped")
    stats.record("d", "error", detail="boom")
    t = stats.totals()
    assert t["programs"] == 4 and t["errors"] == 1
    assert t["persistent_hits"] == 1 and t["persistent_misses"] == 1
    h = stats.headline()
    assert h["compile_cache_programs"] == 4
    assert h["compile_cache_compiled"] == 2      # compiled + executed
    assert h["compile_cache_skipped"] == 1
    assert h["compile_cache_trace_lower_seconds"] == 1.5
    assert h["compile_cache_persistent_hits"] == 1
    assert h["compile_cache_persistent_misses"] == 1
    assert "a" in stats.table() and "error" in stats.table()


def test_trace_guard_raises_recursion_limit():
    before = sys.getrecursionlimit()
    cc.trace_guard(min_recursion=max(before, 20000))
    assert sys.getrecursionlimit() >= 20000


def test_cli_list_exits_zero(capsys):
    from drynx_tpu import precompile as cli

    assert cli.main(["--list"]) == 0
    out = capsys.readouterr().out
    assert "bucketed:fn_add" in out and "fused:dec" in out
    assert "programs" in out


def test_cli_list_shards_includes_shard_programs(capsys):
    from drynx_tpu import precompile as cli

    assert cli.main(["--list", "--shards", "8"]) == 0
    out = capsys.readouterr().out
    assert "RangeProofVerifyShard" in out
    assert "RangeProofCreateShard" in out
    # and forcing a single shard removes them again
    assert cli.main(["--list", "--shards", "1"]) == 0
    out = capsys.readouterr().out
    assert "RangeProofVerifyShard" not in out


def test_cli_list_buckets_includes_tile_programs(capsys):
    from drynx_tpu import precompile as cli

    assert cli.main(["--list", "--buckets", "65536", "--values", "65536",
                     "--range-u", "2", "--range-l", "1"]) == 0
    out = capsys.readouterr().out
    assert "RangeProofCreateTile" in out
    assert "fused:enc@" in out
    # no grid axis -> no tile programs
    assert cli.main(["--list"]) == 0
    out = capsys.readouterr().out
    assert "RangeProofCreateTile" not in out


def test_registry_pool_program_set():
    """Profile.n_noise > 0 must add the DRO pool/slab programs (the raw
    jits the precompute refill + shuffle paths dispatch) at exactly the
    dro.slab_widths chunk widths plus the monolithic width — and must
    only ever ADD programs: the non-diffp registry stays a strict subset,
    so pooling can never silently drop AOT coverage."""
    from drynx_tpu.parallel import dro

    base = cc.BENCH
    pooled = cc.build_registry(dataclasses_replace(base, n_noise=10000))
    base_names = {s.name for s in cc.build_registry(base)}
    pooled_names = {s.name for s in pooled}
    assert base_names <= pooled_names
    extra = [s for s in pooled if s.name not in base_names]
    assert extra, "n_noise must add pool programs"
    assert {s.phase for s in extra} == {"DROPool"}
    assert {s.kind for s in extra} == {"pool"}
    # every slab width the chunked path dispatches is certified
    widths = set(dro.slab_widths(10000)) | {10000}
    for op in ("encrypt_with_tables", "int_to_scalar", "ct_add"):
        got = {int(s.name.rsplit("@", 1)[1]) for s in extra if s.op == op}
        assert got == widths, (op, got, widths)
    # pool programs always dispatch (plain device jits, no backend gate)
    assert all(s.dispatched() for s in extra)


def test_registry_wire_widen_complete(registry):
    """Every (narrow, wide) dtype pair the v2 encoder can ship has a
    registered on-device widen program — a new _NARROW entry in
    transport without a registry program fails here. Wire programs are
    profile-independent: present in every registry, always dispatched."""
    from drynx_tpu.service import transport as T

    wire = [s for s in registry if s.kind == "wire"]
    names = {s.name for s in wire}
    for narrow, orig in T.widen_pairs():
        assert f"wire:widen@{narrow}->{orig}" in names, (narrow, orig)
    assert len(wire) == len(T.widen_pairs())
    assert {s.phase for s in wire} == {"WireDecode"}
    assert all(s.dispatched() for s in wire)
    # profile-independence: the smallest profile certifies the same set
    small = cc.build_registry(cc.Profile(n_cns=2, n_dps=2, n_values=2,
                                         u=4, l=2, dlog_limit=100))
    assert {s.name for s in small if s.kind == "wire"} == names


def test_cli_list_includes_wire_programs(capsys):
    from drynx_tpu import precompile as cli

    assert cli.main(["--list"]) == 0
    out = capsys.readouterr().out
    assert "wire:widen@uint16->uint32" in out
    assert "WireDecode" in out


def test_registry_n_noise_zero_is_identity():
    base = cc.BENCH
    zero = cc.build_registry(dataclasses_replace(base, n_noise=0))
    assert {s.name for s in zero} == {s.name
                                      for s in cc.build_registry(base)}


def test_cli_list_noise_includes_pool_programs(capsys):
    from drynx_tpu import precompile as cli

    assert cli.main(["--list", "--noise", "10000"]) == 0
    out = capsys.readouterr().out
    assert "pool:encrypt_with_tables@4096" in out
    assert "DROPool" in out
    # no diffp axis -> no pool programs
    assert cli.main(["--list"]) == 0
    out = capsys.readouterr().out
    assert "DROPool" not in out


def test_registry_pane_program_set():
    """Profile.n_pane > 1 must add the streaming pane-delta programs —
    the RAW ct_add/ct_sub jits at the (V,) window-aggregate shape plus
    the first advance's bucketed pane-stack fold — and must only ever
    ADD programs: the one-shot registry stays a strict subset, mirroring
    the n_fold / n_noise contracts."""
    base = cc.BENCH
    paned = cc.build_registry(dataclasses_replace(base, n_pane=16))
    base_names = {s.name for s in cc.build_registry(base)}
    paned_names = {s.name for s in paned}
    assert base_names <= paned_names
    extra = [s for s in paned if s.name not in base_names]
    assert extra, "n_pane=16 must add pane-delta programs"
    phases = {s.phase for s in extra}
    assert phases <= {"PaneDelta", "PaneFold"}
    assert "PaneDelta" in phases
    # the raw delta jits at the window shape, both directions
    assert f"pane:ct_add@{base.n_values}" in paned_names
    assert f"pane:ct_sub@{base.n_values}" in paned_names
    # pane programs always dispatch (plain device jits, no backend gate)
    assert all(s.dispatched() for s in extra if s.kind == "pane")


def test_registry_n_pane_zero_and_one_are_identity():
    """n_pane in {0, 1} means no delta chain (a 1-pane window re-folds
    from scratch), so the registry must be exactly the one-shot set."""
    base = cc.BENCH
    base_names = {s.name for s in cc.build_registry(base)}
    for n in (0, 1):
        same = cc.build_registry(dataclasses_replace(base, n_pane=n))
        assert {s.name for s in same} == base_names, n


def test_cli_list_panes_includes_pane_programs(capsys):
    from drynx_tpu import precompile as cli

    assert cli.main(["--list", "--panes", "16"]) == 0
    out = capsys.readouterr().out
    assert "pane:ct_sub@9" in out
    assert "PaneDelta" in out
    # no streaming axis -> no pane programs
    assert cli.main(["--list"]) == 0
    out = capsys.readouterr().out
    assert "PaneDelta" not in out
