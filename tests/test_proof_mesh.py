"""Mesh proof plane: sharded digit-proof creation and RLC verification on
the conftest 8-device CPU mesh must be BIT-IDENTICAL to the single-device
path — same transcripts, same GT total, same accept/reject decision — and
the plane must be the DEFAULT route whenever >= 2 devices are visible.

Default tier (this file, CPU-safe): the chunked per-device strategy
(parallel/proof_plane.py dispatch), which reuses the single-device bucketed
programs per shard — on CPU they detour to the host oracle, so there is no
XLA pairing compile to pay. The monolithic shard_map SPMD strategy stays
opt-in at the bottom (pytest.mark.slow + DRYNX_MESH_COMPILE_TESTS=1): its
jnp-pairing compile exceeded 90 minutes of XLA CPU time on a 1-core box
(round-4 measurement) because a shard_map body must stay traceable and
cannot take the host-oracle detour.
"""
import dataclasses as dc
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from drynx_tpu.crypto import elgamal as eg
from drynx_tpu.crypto import field as F
from drynx_tpu.crypto import fp12 as F12
from drynx_tpu.parallel import proof_mesh as pm
from drynx_tpu.parallel import proof_plane as plane
from drynx_tpu.proofs import range_proof as rp

RNG = np.random.default_rng(71)
U, L, NS = 4, 2, 2          # values in [0, 16), 2 servers


@pytest.fixture(scope="module")
def setup():
    sigs = [rp.init_range_sig(U, RNG) for _ in range(NS)]
    _, ca_pub = eg.keygen(RNG)
    ca_tbl = eg.pub_table(ca_pub)
    values = np.asarray([3, 15, 0, 7], dtype=np.int64)
    cts, rs = eg.encrypt_ints(jax.random.PRNGKey(72), ca_tbl, values)
    # canonical transcript: explicit single-device creation
    proof = rp.create_range_proofs(
        jax.random.PRNGKey(73), values, rs, cts, sigs, U, L, ca_tbl.table,
        shard=False)
    return sigs, ca_tbl, values, cts, rs, proof


def test_plane_is_default_on_the_8_device_mesh():
    assert plane.device_count() >= 8
    assert plane.n_shards() >= 8
    assert plane.enabled()


def test_plane_policy_env(monkeypatch):
    monkeypatch.setenv(plane.ENV_FLAG, "off")
    assert plane.n_shards() == 1 and not plane.enabled()
    monkeypatch.setenv(plane.ENV_FLAG, "3")
    assert plane.n_shards() == 3 and plane.enabled()
    monkeypatch.setenv(plane.ENV_FLAG, "auto")
    assert plane.n_shards() == plane.device_count()


def test_shard_slices_partition():
    for n, k in [(1, 8), (7, 8), (8, 8), (17, 8), (64, 8), (5, 1), (0, 8)]:
        slices = plane.shard_slices(n, k)
        if n == 0:
            assert slices == []
            continue
        # contiguous partition of range(n), no empty shard, balanced
        assert slices[0][0] == 0 and slices[-1][1] == n
        sizes = [b - a for a, b in slices]
        assert min(sizes) >= 1
        assert max(sizes) - min(sizes) <= 1
        assert all(slices[i][1] == slices[i + 1][0]
                   for i in range(len(slices) - 1))
        assert len(slices) <= k


def test_sharded_creation_transcript_identical(setup):
    """shard=True must produce byte-for-byte the same proof batch: the
    Fiat-Shamir hash covers the commitments, so ANY drift would flip the
    challenge and break verification everywhere."""
    sigs, ca_tbl, values, cts, rs, proof = setup
    sharded = rp.create_range_proofs(
        jax.random.PRNGKey(73), values, rs, cts, sigs, U, L, ca_tbl.table,
        shard=True)
    assert sharded.to_bytes() == proof.to_bytes()
    # and the default (shard=None) routes to the sharded path on this mesh
    default = rp.create_range_proofs(
        jax.random.PRNGKey(73), values, rs, cts, sigs, U, L, ca_tbl.table)
    assert default.to_bytes() == proof.to_bytes()


def test_sharded_total_bit_identical(setup):
    """Same verifier weight draw => np.array_equal GT totals (not just
    equal as field elements: identical canonical limb arrays)."""
    sigs, ca_tbl, _, _, _, proof = setup
    pubs = [s.public for s in sigs]
    pre_ok, r_int, gtb_pow_s = rp.rlc_prelude(
        proof, pubs, ca_tbl.table, rng=np.random.default_rng(5))
    assert pre_ok
    single = np.asarray(rp.rlc_total_single(proof, pubs, r_int, gtb_pow_s))
    shards = np.asarray(pm.rlc_total_shards(proof, pubs, r_int, gtb_pow_s))
    assert np.array_equal(single, shards)
    # honest proof: the shared total IS the GT identity
    assert bool(np.asarray(F12.eq(jnp.asarray(shards),
                                  jnp.asarray(F12.one()))))
    # n_shards=1 is the single-device fallback, same arrays again
    one = np.asarray(pm.rlc_total_shards(proof, pubs, r_int, gtb_pow_s,
                                         n_shards=1))
    assert np.array_equal(single, one)


def test_sharded_verify_agrees_with_single_device(setup):
    sigs, ca_tbl, _, _, _, proof = setup
    pubs = [s.public for s in sigs]
    assert pm.rlc_verify_sharded(proof, pubs, ca_tbl.table,
                                 rng=np.random.default_rng(6))
    assert rp.verify_range_proofs_batch(proof, pubs, ca_tbl.table,
                                        rng=np.random.default_rng(6))


def test_sharded_verify_rejects_tampering(setup):
    sigs, ca_tbl, _, _, _, proof = setup
    pubs = [s.public for s in sigs]
    bad_zv = np.asarray(proof.zv).copy()
    bad_zv[0, 0, 0, 0] ^= 1
    bad = dc.replace(proof, zv=jnp.asarray(bad_zv))
    assert not pm.rlc_verify_sharded(bad, pubs, ca_tbl.table,
                                     rng=np.random.default_rng(7))
    # challenge binding also enforced on the sharded path
    bad2 = dc.replace(proof, a=F.neg(jnp.asarray(proof.a), F.FP))
    assert not pm.rlc_verify_sharded(bad2, pubs, ca_tbl.table,
                                     rng=np.random.default_rng(8))


def test_safe_batch_verify_routes_to_the_plane(setup, monkeypatch):
    """service-layer joint-range verification must take the sharded path by
    default on this mesh (and still accept)."""
    sigs, ca_tbl, _, _, _, proof = setup
    pubs = [s.public for s in sigs]
    calls = []
    real = pm.rlc_verify_sharded

    def counting(*a, **k):
        calls.append(1)
        return real(*a, **k)

    monkeypatch.setattr(pm, "rlc_verify_sharded", counting)
    assert rp._safe_batch_verify(proof, pubs, ca_tbl.table)
    assert len(calls) == 1

    # plane off => the single-device verifier, no sharded call
    monkeypatch.setenv(plane.ENV_FLAG, "off")
    calls.clear()
    assert rp._safe_batch_verify(proof, pubs, ca_tbl.table)
    assert calls == []


def test_safe_batch_verify_contains_sharded_failure(setup, monkeypatch):
    """A crash inside the sharded path must fall back to the single-device
    verifier, not reject an honest payload."""
    sigs, ca_tbl, _, _, _, proof = setup
    pubs = [s.public for s in sigs]

    def boom(*a, **k):
        raise RuntimeError("injected shard failure")

    monkeypatch.setattr(pm, "rlc_verify_sharded", boom)
    assert rp._safe_batch_verify(proof, pubs, ca_tbl.table)


# ---------------------------------------------------------------------------
# The monolithic shard_map SPMD strategy (slow, opt-in): one giant traced
# program over a real jax.sharding.Mesh. Kept as the on-chip strategy
# ("strategy='spmd'"); its XLA CPU compile of the jnp pairing (65-step
# Miller scan + GT pow in one SPMD body) exceeded 90 min on a 1-core box.
# ---------------------------------------------------------------------------

def _mesh():
    # 2x2 mesh (not the full 8): the mesh axes are FLATTENED to one shard
    # axis inside rlc_total_sharded, so 4 devices exercise the same
    # sharding + GT all-reduce semantics while the SPMD program's unrolled
    # butterfly (log2 rounds) compiles in half the time
    devs = jax.devices()
    assert len(devs) >= 8, "conftest must provide the 8-device CPU mesh"
    return jax.sharding.Mesh(np.asarray(devs[:4]).reshape(2, 2),
                             ("dp", "ct"))


@pytest.mark.slow
@pytest.mark.skipif(
    os.environ.get("DRYNX_MESH_COMPILE_TESTS", "0") != "1",
    reason="shard_map jnp-pairing compile >90 min CPU; opt in with "
           "DRYNX_MESH_COMPILE_TESTS=1")
def test_spmd_total_matches_single_device(setup):
    sigs, ca_tbl, _, _, _, proof = setup
    pubs = [s.public for s in sigs]
    pre_ok, r_int, gtb_pow_s = rp.rlc_prelude(
        proof, pubs, ca_tbl.table, rng=np.random.default_rng(5))
    assert pre_ok
    total = pm.rlc_total_sharded(_mesh(), proof, pubs, r_int, gtb_pow_s)
    assert bool(np.asarray(F12.eq(total, jnp.asarray(F12.one()))))
    assert pm.rlc_verify_sharded(proof, pubs, ca_tbl.table,
                                 rng=np.random.default_rng(6),
                                 mesh=_mesh(), strategy="spmd")


@pytest.mark.slow
@pytest.mark.skipif(
    os.environ.get("DRYNX_MESH_COMPILE_TESTS", "0") != "1",
    reason="shard_map jnp-pairing compile >90 min CPU; opt in with "
           "DRYNX_MESH_COMPILE_TESTS=1")
def test_spmd_verify_rejects_tampering(setup):
    sigs, ca_tbl, _, _, _, proof = setup
    pubs = [s.public for s in sigs]
    bad_zv = np.asarray(proof.zv).copy()
    bad_zv[0, 0, 0, 0] ^= 1
    bad = dc.replace(proof, zv=jnp.asarray(bad_zv))
    assert not pm.rlc_verify_sharded(bad, pubs, ca_tbl.table,
                                     rng=np.random.default_rng(7),
                                     mesh=_mesh(), strategy="spmd")
