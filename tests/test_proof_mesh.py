"""Mesh-sharded RLC range-proof verification (round-2 VERDICT weak #6 /
task 6): the pairing-heavy batch check rides the virtual 8-device CPU mesh
and must agree EXACTLY (bit-identical GT total) with the single-device path.
"""
import dataclasses as dc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from drynx_tpu.crypto import batching as B
from drynx_tpu.crypto import elgamal as eg
from drynx_tpu.crypto import fp12 as F12
from drynx_tpu.crypto import params
from drynx_tpu.parallel import proof_mesh as pm

# The shard_map compile of the jnp pairing (65-step Miller scan + GT pow
# inside one SPMD program) exceeds 90 minutes of XLA CPU compile on this
# 1-core box under jax 0.8 — even after shrinking the pow to 63 bits and
# the mesh to 2x2 (measured round 4; the per-element math itself is
# oracle-fast everywhere else via crypto/host_oracle.py, but a shard_map
# body must stay traceable so it cannot take the host path). The mesh
# path's acceptance predicate is identical to the single-device verifier
# by construction (rlc_prelude is SHARED), and that verifier's soundness
# suite runs in minutes (tests/test_range_proof.py). Opt in explicitly:
import os

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        os.environ.get("DRYNX_MESH_COMPILE_TESTS", "0") != "1",
        reason="shard_map jnp-pairing compile >90 min CPU; opt in with "
               "DRYNX_MESH_COMPILE_TESTS=1"),
]
from drynx_tpu.proofs import range_proof as rp

RNG = np.random.default_rng(71)
U, L, NS = 4, 2, 2          # values in [0, 16), 2 servers


@pytest.fixture(scope="module")
def setup():
    sigs = [rp.init_range_sig(U, RNG) for _ in range(NS)]
    _, ca_pub = eg.keygen(RNG)
    ca_tbl = eg.pub_table(ca_pub)
    values = np.asarray([3, 15, 0, 7], dtype=np.int64)
    cts, rs = eg.encrypt_ints(jax.random.PRNGKey(72), ca_tbl, values)
    proof = rp.create_range_proofs(
        jax.random.PRNGKey(73), values, rs, cts, sigs, U, L, ca_tbl.table)
    return sigs, ca_tbl, proof


def _mesh():
    # 2x2 mesh (not the full 8): the mesh axes are FLATTENED to one shard
    # axis inside rlc_total_sharded, so 4 devices exercise the same
    # sharding + GT all-reduce semantics while the SPMD program's unrolled
    # butterfly (log2 rounds) compiles in half the time — this file's
    # shard_map jnp-pairing compile is the suite's single heaviest
    devs = jax.devices()
    assert len(devs) >= 8, "conftest must provide the 8-device CPU mesh"
    return jax.sharding.Mesh(np.asarray(devs[:4]).reshape(2, 2),
                             ("dp", "ct"))


def test_sharded_total_matches_single_device(setup):
    """Same verifier randomness => bit-identical GT total on the mesh."""
    sigs, ca_tbl, proof = setup
    pubs = [s.public for s in sigs]
    pre_ok, r_int, gtb_pow_s = rp.rlc_prelude(
        proof, pubs, ca_tbl.table, rng=np.random.default_rng(5))
    assert pre_ok

    total = pm.rlc_total_sharded(_mesh(), proof, pubs, r_int, gtb_pow_s)
    # honest proof: the total IS the identity (this is also the
    # single-device acceptance condition, so equality with it is implied)
    assert bool(np.asarray(F12.eq(total, jnp.asarray(F12.one()))))

    # and the full sharded verdict agrees with the host verifier
    assert pm.rlc_verify_sharded(_mesh(), proof, pubs, ca_tbl.table,
                                 rng=np.random.default_rng(6))
    assert rp.verify_range_proofs_batch(proof, pubs, ca_tbl.table,
                                        rng=np.random.default_rng(6))


def test_sharded_verify_rejects_tampering(setup):
    sigs, ca_tbl, proof = setup
    pubs = [s.public for s in sigs]
    bad_zv = np.asarray(proof.zv).copy()
    bad_zv[0, 0, 0, 0] ^= 1
    bad = dc.replace(proof, zv=jnp.asarray(bad_zv))
    assert not pm.rlc_verify_sharded(_mesh(), bad, pubs, ca_tbl.table,
                                     rng=np.random.default_rng(7))
    # challenge binding also enforced on the sharded path
    from drynx_tpu.crypto import field as F

    bad2 = dc.replace(proof, a=F.neg(jnp.asarray(proof.a), F.FP))
    assert not pm.rlc_verify_sharded(_mesh(), bad2, pubs, ca_tbl.table,
                                     rng=np.random.default_rng(8))
