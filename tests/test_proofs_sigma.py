"""Obfuscation / key-switch / aggregation proofs + Schnorr + request layer."""
import pytest

pytestmark = pytest.mark.slow  # compiles crypto kernels; fast tier = -m "not slow"

import numpy as np
import jax
import jax.numpy as jnp

from drynx_tpu.crypto import curve as C
from drynx_tpu.crypto import elgamal as eg
from drynx_tpu.parallel import collective as col
from drynx_tpu.proofs import aggregation as ap
from drynx_tpu.proofs import keyswitch as kp
from drynx_tpu.proofs import obfuscation as op
from drynx_tpu.proofs import requests as rq
from drynx_tpu.proofs import schnorr

RNG = np.random.default_rng(17)


@pytest.fixture(scope="module")
def keys():
    x, pub = eg.keygen(RNG)
    return x, pub, eg.pub_table(pub)


def test_schnorr_sign_verify(keys):
    x, pub, _ = keys
    sig = schnorr.sign(x, b"hello drynx")
    assert schnorr.verify(pub, b"hello drynx", sig)
    assert not schnorr.verify(pub, b"tampered", sig)
    got = schnorr.verify_batch([pub, pub], [b"a", b"b"],
                               [schnorr.sign(x, b"a"), schnorr.sign(x, b"b")])
    assert got.tolist() == [True, True]


def test_obfuscation_proof_roundtrip(keys):
    _, _, tbl = keys
    vals = np.asarray([3, 0, 7], dtype=np.int64)
    cts, _ = eg.encrypt_ints(jax.random.PRNGKey(1), tbl, vals)
    s = eg.random_scalars(jax.random.PRNGKey(2), (3,))
    proof = op.create_obfuscation_proofs(jax.random.PRNGKey(3), cts, s)
    assert op.verify_obfuscation_proofs(proof).tolist() == [True] * 3
    # tamper: claim a different obfuscated ciphertext
    s2 = eg.random_scalars(jax.random.PRNGKey(4), (3,))
    bad = op.ObfuscationProofBatch(
        orig=proof.orig, obf=eg.ct_scalar_mul(cts, s2), a1=proof.a1,
        a2=proof.a2, challenge=proof.challenge, z=proof.z)
    assert not bool(np.all(op.verify_obfuscation_proofs(bad)))
    assert op.verify_obfuscation_list(proof, threshold=0.5)


def test_keyswitch_proof_roundtrip(keys):
    x, pub, tbl = keys
    ns, V = 3, 4
    rng = np.random.default_rng(23)
    secrets, pubs = zip(*[eg.keygen(rng) for _ in range(ns)])
    srv_x = jnp.asarray(np.stack([eg.secret_to_limbs(s) for s in secrets]))
    coll_tbl = eg.pub_table(col.collective_key(pubs))

    vals = np.asarray([1, -2, 5, 0], dtype=np.int64)
    cts, _ = eg.encrypt_ints(jax.random.PRNGKey(7), coll_tbl, vals)
    ks_rs = eg.random_scalars(jax.random.PRNGKey(8), (ns, V))
    u_pts, w_pts = jax.vmap(
        lambda sx, r: col.keyswitch_contribution(cts, sx, r, tbl.table)
    )(srv_x, ks_rs)

    q_pt = jnp.asarray(C.from_ref(pub))
    proof = kp.create_keyswitch_proofs(
        jax.random.PRNGKey(9), cts[:, 0], srv_x, ks_rs, q_pt, tbl.table,
        u_pts, w_pts)
    ok = kp.verify_keyswitch_proofs(proof, tbl.table)
    assert bool(np.all(ok)), ok

    # a lying server (wrong secret in the contribution) must fail
    bad_w = w_pts.at[0].set(w_pts[1])
    bad = kp.create_keyswitch_proofs(
        jax.random.PRNGKey(10), cts[:, 0], srv_x, ks_rs, q_pt, tbl.table,
        u_pts, bad_w)
    assert not bool(np.all(kp.verify_keyswitch_proofs(bad, tbl.table)))
    assert kp.verify_keyswitch_list(proof, tbl.table, threshold=0.5)


def test_aggregation_proof(keys):
    _, _, tbl = keys
    vals = np.asarray([[1, 2], [3, 4], [5, 6]], dtype=np.int64)  # 3 DPs, V=2
    cts, _ = eg.encrypt_ints(jax.random.PRNGKey(11), tbl, vals)
    agg = C.add(C.add(cts[0], cts[1]), cts[2])
    proof = ap.create_aggregation_proof(cts, agg)
    assert ap.verify_aggregation_proof(proof).tolist() == [True, True]
    bad = ap.create_aggregation_proof(cts, C.add(agg, cts[0]))
    assert not bool(np.all(ap.verify_aggregation_proof(bad)))
    assert ap.verify_aggregation_list(proof, threshold=1.0)


def test_proof_request_bitmap_codes(keys):
    x, pub, _ = keys
    req = rq.new_proof_request("aggregation", "sv1", "dp0", "g0", 0,
                               b"payload-bytes", x)
    rng = np.random.default_rng(0)
    # good signature + always-sampled + passing payload -> BM_TRUE
    assert rq.verify_proof_request(req, pub, 1.0, lambda d, sv: True, rng) == rq.BM_TRUE
    # failing payload -> BM_FALSE
    assert rq.verify_proof_request(req, pub, 1.0, lambda d, sv: False, rng) == rq.BM_FALSE
    # sampling off -> BM_RECVD
    assert rq.verify_proof_request(req, pub, 0.0, lambda d, sv: True, rng) == rq.BM_RECVD
    # wrong sender key -> BM_BADSIG
    other = eg.keygen(np.random.default_rng(99))[1]
    assert rq.verify_proof_request(req, other, 1.0, lambda d, sv: True, rng) == rq.BM_BADSIG
    assert req.storage_key() == "sv1/aggregation/dp0/g0"
