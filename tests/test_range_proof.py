"""Range-proof layer vs reference semantics: create -> verify round trip,
tamper rejection, serialization, and the GT pow_var kernel.

Mirrors the reference's test pattern (lib/range/range_proof_test.go:14-77:
create proof for a value in [0, u^l), verify true; out-of-range or corrupted
proofs verify false)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from drynx_tpu.crypto import elgamal as eg
from drynx_tpu.crypto import fp12 as F12
from drynx_tpu.crypto import field as F
from drynx_tpu.crypto import params, refimpl
from drynx_tpu.proofs import range_proof as rp

pytestmark = pytest.mark.slow  # heavy compiles; fast tier = -m 'not slow'

RNG = np.random.default_rng(7)
U, L = 4, 3          # values in [0, 64)
NS = 2               # servers


@pytest.fixture(scope="module")
def setup():
    sigs = [rp.init_range_sig(U, RNG) for _ in range(NS)]
    ca_secret, ca_pub = eg.keygen(RNG)
    ca_tbl = eg.pub_table(ca_pub)
    return sigs, ca_secret, ca_pub, ca_tbl


def test_fp12_pow_var_matches_pow_const():
    f = refimpl.pair(refimpl.G1, refimpl.G2)
    df = jnp.asarray(F12.from_ref(f))
    e = 0x1234567890ABCDEF
    got = F12.pow_var(df, jnp.asarray(F.from_int(e)))
    want = F12.pow_const(df, e)
    assert bool(jnp.all(F12.eq(got, want)))


def test_to_base_matches_reference_semantics():
    # reference ToBase(n, b, l): little-endian digits padded to l
    assert rp.to_base(np.asarray([13]), 4, 3).tolist() == [[1, 3, 0]]
    assert rp.to_base(np.asarray([0]), 2, 4).tolist() == [[0, 0, 0, 0]]


def test_range_proof_roundtrip(setup):
    sigs, _, _, ca_tbl = setup
    values = np.asarray([0, 13, 63], dtype=np.int64)
    key = jax.random.PRNGKey(3)
    cts, rs = eg.encrypt_ints(key, ca_tbl, values)
    proof = rp.create_range_proofs(
        jax.random.PRNGKey(5), values, rs, cts, sigs, U, L, ca_tbl.table)
    ok = rp.verify_range_proofs(proof, [s.public for s in sigs], ca_tbl.table)
    assert ok.tolist() == [True, True, True]


def test_range_proof_rejects_tampered_value(setup):
    sigs, _, _, ca_tbl = setup
    values = np.asarray([5], dtype=np.int64)
    key = jax.random.PRNGKey(11)
    cts, rs = eg.encrypt_ints(key, ca_tbl, values)
    proof = rp.create_range_proofs(
        jax.random.PRNGKey(12), values, rs, cts, sigs, U, L, ca_tbl.table)

    # tamper 1: swap the commit for an encryption of a different value
    cts2, _ = eg.encrypt_ints(jax.random.PRNGKey(13), ca_tbl,
                              np.asarray([6], dtype=np.int64))
    bad = rp.RangeProofBatch(
        commit=cts2, challenge=proof.challenge, zr=proof.zr, d=proof.d,
        zphi=proof.zphi, zv=proof.zv, v_pts=proof.v_pts, a=proof.a, u=U, l=L)
    assert not bool(np.all(rp.verify_range_proofs(
        bad, [s.public for s in sigs], ca_tbl.table)))

    # tamper 2: corrupt a response scalar
    zphi2 = proof.zphi.at[0, 0, 0].set(proof.zphi[0, 0, 0] ^ 1)
    bad2 = rp.RangeProofBatch(
        commit=proof.commit, challenge=proof.challenge, zr=proof.zr,
        d=proof.d, zphi=zphi2, zv=proof.zv, v_pts=proof.v_pts, a=proof.a,
        u=U, l=L)
    assert not bool(np.all(rp.verify_range_proofs(
        bad2, [s.public for s in sigs], ca_tbl.table)))


def test_range_proof_wrong_blinding_fails(setup):
    """A prover lying about r (the ElGamal blinding) must fail the D check."""
    sigs, _, _, ca_tbl = setup
    values = np.asarray([7], dtype=np.int64)
    cts, rs = eg.encrypt_ints(jax.random.PRNGKey(21), ca_tbl, values)
    wrong_rs = eg.random_scalars(jax.random.PRNGKey(22), (1,))
    proof = rp.create_range_proofs(
        jax.random.PRNGKey(23), values, wrong_rs, cts, sigs, U, L,
        ca_tbl.table)
    assert not bool(np.all(rp.verify_range_proofs(
        proof, [s.public for s in sigs], ca_tbl.table)))


def test_range_proof_serialization_roundtrip(setup):
    sigs, _, _, ca_tbl = setup
    values = np.asarray([42], dtype=np.int64)
    cts, rs = eg.encrypt_ints(jax.random.PRNGKey(31), ca_tbl, values)
    proof = rp.create_range_proofs(
        jax.random.PRNGKey(32), values, rs, cts, sigs, U, L, ca_tbl.table)
    blob = proof.to_bytes()
    back = rp.RangeProofBatch.from_bytes(blob)
    assert back.u == U and back.l == L
    ok = rp.verify_range_proofs(back, [s.public for s in sigs], ca_tbl.table)
    assert bool(np.all(ok))


def test_range_proof_rlc_batch_verify(setup):
    """RLC single-verdict path: accepts good batches, rejects tampering."""
    sigs, _, _, ca_tbl = setup
    pubs = [s.public for s in sigs]
    values = np.asarray([5, 63, 0], dtype=np.int64)
    key = jax.random.PRNGKey(9)
    cts, rs = eg.encrypt_ints(key, ca_tbl, values)
    proof = rp.create_range_proofs(
        jax.random.PRNGKey(6), values, rs, cts, sigs, U, L, ca_tbl.table)
    rng = np.random.default_rng(1)
    assert rp.verify_range_proofs_batch(proof, pubs, ca_tbl.table, rng=rng)
    # tampered a (one GT element replaced) -> reject. wire=None: a modified
    # batch must drop the canonical-byte cache (RangeProofBatch invariant);
    # verification then re-encodes the tampered tensors, which is exactly
    # what a wire-level tamper would deliver.
    bad_a = np.asarray(proof.a).copy()
    bad_a[0, 1] = np.asarray(F12.from_ref(refimpl.pair(refimpl.G1,
                                                       refimpl.G2)))
    import dataclasses as dc
    bad = dc.replace(proof, a=jnp.asarray(bad_a), wire=None)
    assert not rp.verify_range_proofs_batch(bad, pubs, ca_tbl.table,
                                            rng=np.random.default_rng(2))
    # tampered zv -> reject
    bad_zv = np.asarray(proof.zv).copy()
    bad_zv[0, 0, 0, 0] ^= 1
    bad2 = dc.replace(proof, zv=jnp.asarray(bad_zv))
    assert not rp.verify_range_proofs_batch(bad2, pubs, ca_tbl.table,
                                            rng=np.random.default_rng(3))


def _forge_proof(cts, c, zr, zphi, zv, v_pts, sigs_pub, ca_tbl, u, l):
    """Build the derive-D-and-a forgery (round-2 VERDICT weak #2): with
    c fixed FIRST and Zphi/Zr/Zv/V chosen freely, D and a are DERIVED from
    the two verifier equations so both checks pass for a ciphertext
    encrypting ANYTHING. Defeated only by the challenge binding."""
    from drynx_tpu.crypto import batching as B
    from drynx_tpu.crypto import curve as C

    base_tbl = eg.BASE_TABLE.table
    ys = jnp.asarray(np.stack([C.from_ref(p) for p in sigs_pub]))
    C2 = jnp.asarray(cts)[..., 1, :, :]
    wz = rp._weighted_sum_mod_n(zphi, rp._upow_mont(u, l))
    D = B.g1_add(B.g1_scalar_mul(C2, c),
                 B.g1_add(B.fixed_base_mul(ca_tbl.table, zr),
                          B.fixed_base_mul(base_tbl, wz)))
    cy = B.g1_scalar_mul(ys[:, None, :, :], c[None, :, :])
    nzphiB = B.fixed_base_mul(base_tbl, B.fn_neg(zphi))
    g1arg = B.g1_add(cy[:, :, None, :, :], nzphiB[None])
    px, py, _ = B.g1_normalize(g1arg)
    qx, qy, _ = B.g2_normalize(v_pts)
    a = B.gt_mul(B.pair(px, py, qx, qy), rp.gt_pow_gtb(zv))
    return rp.RangeProofBatch(commit=jnp.asarray(cts), challenge=c, zr=zr,
                              d=D, zphi=zphi, zv=zv, v_pts=v_pts, a=a,
                              u=u, l=l)


def test_derived_commitment_forgery_rejected(setup):
    """VERDICT round-2 weak #2 regression: a proof whose D and a are derived
    from the verifier equations AFTER fixing c must be rejected — and it
    MUST be the challenge binding that rejects it (the equation checks pass
    by construction, demonstrating the attack is faithfully emulated)."""
    sigs, _, _, ca_tbl = setup
    pubs = [s.public for s in sigs]
    # ciphertext encrypts 1000, far outside [0, u^l) = [0, 64)
    out_of_range = np.asarray([1000], dtype=np.int64)
    cts, _ = eg.encrypt_ints(jax.random.PRNGKey(41), ca_tbl, out_of_range)

    # adversary: pick c and all responses freely (c BEFORE D/V/a)
    c = eg.random_scalars(jax.random.PRNGKey(42), (1,))
    zr = eg.random_scalars(jax.random.PRNGKey(43), (1,))
    zphi = eg.random_scalars(jax.random.PRNGKey(44), (1, L))
    zv = eg.random_scalars(jax.random.PRNGKey(45), (NS, 1, L))
    # arbitrary valid G2 points for V: blinded copies of a digit signature
    v_blind = eg.random_scalars(jax.random.PRNGKey(46), (NS, 1, L))
    from drynx_tpu.crypto import batching as B
    A_sel = jnp.asarray(np.stack([s.A for s in sigs]))[:, np.zeros((1, L),
                                                                   np.int32)]
    v_pts = B.g2_scalar_mul(A_sel, v_blind)

    forged = _forge_proof(cts, c, zr, zphi, zv, v_pts, pubs, ca_tbl, U, L)

    # equations alone accept the forgery (this is the round-2 hole) ...
    eq_only = rp.verify_range_proofs(forged, pubs, ca_tbl.table,
                                     check_challenge=False)
    assert bool(np.all(eq_only)), "forgery construction broken: equations " \
                                  "should hold by derivation"
    # ... but the bound Fiat-Shamir challenge rejects it deterministically
    assert not bool(np.any(rp.verify_range_proofs(forged, pubs,
                                                  ca_tbl.table)))
    assert not rp.verify_range_proofs_batch(forged, pubs, ca_tbl.table,
                                            rng=np.random.default_rng(4))


def test_rlc_small_order_forgery_rejected(setup):
    """VERDICT round-2 weak #3 regression: a_ij := -a'_ij makes the RLC
    factor -1, which passed the (challenge-unbound) batch verifier with
    probability 1/2 per attempt. With a bound into the Fiat-Shamir hash the
    rejection is deterministic — every seed must reject."""
    import dataclasses as dc
    sigs, _, _, ca_tbl = setup
    pubs = [s.public for s in sigs]
    values = np.asarray([5], dtype=np.int64)
    cts, rs = eg.encrypt_ints(jax.random.PRNGKey(51), ca_tbl, values)
    proof = rp.create_range_proofs(
        jax.random.PRNGKey(52), values, rs, cts, sigs, U, L, ca_tbl.table)
    # -a: order-2 RLC factor; wire=None so the verifier hashes the tampered
    # encoding (what the wire would carry) — see RangeProofBatch invariant
    neg_a = F.neg(jnp.asarray(proof.a), F.FP)
    bad = dc.replace(proof, a=neg_a, wire=None)
    for seed in range(8):
        assert not rp.verify_range_proofs_batch(
            bad, pubs, ca_tbl.table, rng=np.random.default_rng(seed)), \
            f"small-order forgery accepted with rng seed {seed}"


class _FixedRng:
    """Deterministic stand-in for rlc_prelude's weight draw: weight
    [0, 0, 0] is fixed, all others 1 — isolates the tampered factor."""

    def __init__(self, r0):
        self.r0 = r0

    def integers(self, lo, hi, size=None, dtype=None):
        r = np.full(size, 1, dtype=dtype)
        r[0, 0, 0] = self.r0
        return r


def test_rlc_cofactor_forgery_rejected(setup):
    """Round-4 advisor finding (medium): GΦ12 has order n·c with 13 | c, so
    a COMMIT-FIRST forger can set a' = a_honest·eps (eps of order 13)
    BEFORE the Fiat-Shamir hash — the challenge binding, the D equation,
    and the GΦ12 membership gate all pass, and the RLC draw then accepts
    whenever 13 | r for the tampered weight (probability 1/13 per draw).
    rlc_prelude's order-n gate (gt_order_ok) must reject it for every
    draw."""
    from drynx_tpu.crypto import batching as B
    from drynx_tpu.crypto import host_oracle as ho

    sigs, _, _, ca_tbl = setup
    pubs = [s.public for s in sigs]
    values = np.asarray([5], dtype=np.int64)
    cts, rs = eg.encrypt_ints(jax.random.PRNGKey(61), ca_tbl, values)
    eps = refimpl.gphi12_cofactor_element(13)

    # commit-first forgery: honest commit stage, tamper a BEFORE hashing,
    # then compute honest responses from the tampered-transcript challenge
    ns, V = len(sigs), 1
    digits = jnp.asarray(rp.to_base(values, U, L))
    ks = jax.random.split(jax.random.PRNGKey(62), 4)
    s = eg.random_scalars(ks[0], (V, L))
    t = eg.random_scalars(ks[1], (V, L))
    m = eg.random_scalars(ks[2], (V, L))
    v = eg.random_scalars(ks[3], (ns, V, L))
    A_tab = jnp.asarray(np.stack([sg.A for sg in sigs]))
    D, m_tot, V_pts, a = rp._commit_kernel(
        digits, s, t, m, v, A_tab, ca_tbl.table, U, L,
        gtA=rp.sig_gt_table(sigs))
    a = np.asarray(a).copy()
    a[0, 0, 0] = ho.gt_mul_host(a[0, 0, 0][None],
                                ho._fp12_from_ref(eps)[None])[0]
    a = jnp.asarray(a)
    wire = rp._range_wire_dict(cts, D, V_pts, a)
    c = jnp.asarray(rp.challenge_from_wire(
        wire, rp.sum_publics_bytes(sigs), U, L))
    zphi, zr, zv = rp._response_kernel(digits, c, jnp.asarray(rs), s, t,
                                       m_tot, v)
    forged = rp.RangeProofBatch(commit=jnp.asarray(cts), challenge=c,
                                zr=zr, d=D, zphi=zphi, zv=zv, v_pts=V_pts,
                                a=a, u=U, l=L, wire=wire)

    # the attack is faithfully emulated: binding + GΦ12 both pass ...
    assert bool(np.all(rp._challenge_ok(forged, pubs)))
    assert B.gt_membership_ok(forged.a)
    # ... and WITHOUT the order gate, a 13-divisible weight draw accepts
    # while a non-divisible one rejects — exactly the 1/13 exposure
    orig = B.gt_order_ok
    try:
        B.gt_order_ok = lambda _a: True
        assert rp.verify_range_proofs_batch(
            forged, pubs, ca_tbl.table, rng=_FixedRng(13)), \
            "forgery construction broken: 13|r draw should accept ungated"
        assert not rp.verify_range_proofs_batch(
            forged, pubs, ca_tbl.table, rng=_FixedRng(7))
    finally:
        B.gt_order_ok = orig
    # the order-n gate rejects it regardless of the draw
    assert not B.gt_order_ok(forged.a)
    assert not rp.verify_range_proofs_batch(
        forged, pubs, ca_tbl.table, rng=_FixedRng(13))
    # and honest proofs still pass the gate end-to-end
    honest = rp.create_range_proofs(
        jax.random.PRNGKey(63), values, rs, cts, sigs, U, L, ca_tbl.table)
    assert rp.verify_range_proofs_batch(
        honest, pubs, ca_tbl.table, rng=np.random.default_rng(1))


def test_sig_gt_pow_tables_entries(setup):
    """Per-base GT window tables (creation's squaring-free digit pow):
    T[b][w][j] must equal gtA_b^(j * 16^w) — checked against the oracle on
    a small signature set."""
    from drynx_tpu.crypto import host_oracle as ho

    sigs, _, _, _ = setup
    T = rp.sig_gt_pow_tables(sigs)
    ns, u = len(sigs), sigs[0].u
    assert T.shape == (ns * u, 64, 16, 6, 2, 16)
    gtA = np.asarray(rp.sig_gt_table(sigs))
    for b, w, j in [(0, 0, 0), (0, 0, 1), (1, 0, 3), (ns * u - 1, 2, 5)]:
        base = ho._fp12_to_ref(gtA[b // u, b % u])
        want = refimpl.fp12_pow(base, j * (16 ** w))
        got = ho._fp12_to_ref(T[b, w, j])
        assert got == want, (b, w, j)
