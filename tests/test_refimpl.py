"""Oracle self-tests: the pure-Python bn256 stack must be internally
consistent (group laws, bilinearity, non-degeneracy) before it can serve as
the correctness oracle for the device kernels.

Mirrors the reference's crypto-sanity tier (reference: lib/range/
range_proof_test.go:14-77 exercises pairings; lib/encoding/*_test.go relies on
ElGamal round-trips).
"""
import random

from drynx_tpu.crypto import params, refimpl as r


def test_params():
    assert params.P % 4 == 3
    assert (params.P**12 - 1) % params.N == 0
    assert params.from_limbs(params.to_limbs(params.P - 1)) == params.P - 1


def test_fp2_field():
    rng = random.Random(1)
    for _ in range(20):
        a = (rng.randrange(params.P), rng.randrange(params.P))
        b = (rng.randrange(params.P), rng.randrange(params.P))
        assert r.fp2_mul(a, r.fp2_inv(a)) == r.FP2_ONE
        assert r.fp2_mul(a, b) == r.fp2_mul(b, a)
        assert r.fp2_sq(a) == r.fp2_mul(a, a)
        s = r.fp2_sqrt(r.fp2_sq(a))
        assert s in (a, r.fp2_neg(a))


def test_g1_group_law():
    rng = random.Random(2)
    for _ in range(10):
        k1, k2 = rng.randrange(params.N), rng.randrange(params.N)
        p1, p2 = r.g1_mul(r.G1, k1), r.g1_mul(r.G1, k2)
        assert r.g1_is_on_curve(p1)
        assert r.g1_add(p1, p2) == r.g1_mul(r.G1, (k1 + k2) % params.N)
    assert r.g1_mul(r.G1, params.N) is None
    assert r.g1_add(r.G1, r.g1_neg(r.G1)) is None


def test_g2_group_law():
    rng = random.Random(3)
    k1, k2 = rng.randrange(params.N), rng.randrange(params.N)
    q1, q2 = r.g2_mul(r.G2, k1), r.g2_mul(r.G2, k2)
    assert r.g2_is_on_curve(q1)
    assert r.g2_add(q1, q2) == r.g2_mul(r.G2, (k1 + k2) % params.N)
    assert r.g2_mul_raw(r.G2, params.N) is None  # true order check, no mod


def test_pairing_bilinear_nondegenerate():
    e = r.pair(r.G1, r.G2)
    assert e != r.FP12_ONE
    assert r.fp12_pow(e, params.N) == r.FP12_ONE
    a, b = 987654321, 123456789
    assert r.pair(r.g1_mul(r.G1, a), r.G2) == r.fp12_pow(e, a)
    assert r.pair(r.G1, r.g2_mul(r.G2, b)) == r.fp12_pow(e, b)
    assert r.pair(r.g1_mul(r.G1, a), r.g2_mul(r.G2, b)) == r.fp12_pow(e, a * b % params.N)


def test_cyclotomic_squaring_matches_generic():
    """fp12_csqr (Granger-Scott, the int twin of the Mosaic kernel's
    formulas) must equal the generic square on GΦ12 members — it backs the
    host-oracle order-n gate's pow (batching.gt_order_ok)."""
    e = r.pair(r.G1, r.G2)
    assert r.fp12_csqr(e) == r.fp12_sq(e)
    # chain of 5 squarings stays exact
    x = e
    for _ in range(5):
        x = r.fp12_csqr(x)
    assert x == r.fp12_pow(e, 32)
    # cyc pow with the gate's actual exponent t-1 = p - n
    t1 = params.P - params.N
    assert r.fp12_cyc_pow(e, t1) == r.fp12_pow(e, t1)
    # and a cofactor element (also cyclotomic) squares correctly too
    eps = r.gphi12_cofactor_element(13)
    assert r.fp12_csqr(eps) == r.fp12_sq(eps)
