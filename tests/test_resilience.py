"""Seeded chaos scenarios for the TCP control plane (drynx_tpu/resilience).

Every scenario drives REAL sockets through a deterministic FaultPlan:
dead DPs at dispatch, a DP dying mid-contribution, a straggling VN, and
corrupt/oversized frames. Degraded surveys must still return the correct
aggregate over the responder set, and the same plan seed must produce the
same outcome twice (the acceptance bar in ISSUE/ROBUSTNESS.md).
"""
import socket
import time

import numpy as np
import pytest

from drynx_tpu.crypto import elgamal as eg
from drynx_tpu.resilience import (FaultPlan, FaultSpec, RetryPolicy,
                                  is_idempotent, set_fault_plan)
from drynx_tpu.resilience import policy as rp
from drynx_tpu.service.node import (DrynxNode, RemoteClient, Roster,
                                    RosterEntry, call_entry)
from drynx_tpu.service.transport import (CallTimeout, Conn, ConnectError,
                                         ConnectionClosed, CorruptFrame,
                                         FrameTooLarge, NodeServer,
                                         RemoteError, TransportError,
                                         pack_array, recv_msg)

pytestmark = pytest.mark.chaos

# Chaos tests inject instant faults (refuse / close_mid_frame), so retries
# only cost these short backoffs; the call timeout stays generous because
# a cold process still compiles crypto kernels mid-survey.
FAST = RetryPolicy(connect_retries=1, backoff_s=0.02, backoff_cap_s=0.05,
                   jitter=0.0, call_timeout_s=rp.CALL_TIMEOUT_S, seed=0)


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    set_fault_plan(None)
    yield
    set_fault_plan(None)


def _boot(tmp_path, roles, rng, policy=FAST):
    """Start DrynxNode servers named <role><i> (per-role counters) and
    return (nodes, entries, dp_datas, secrets)."""
    nodes, entries, datas, secrets_of = [], [], {}, {}
    counts = {}
    for role in roles:
        i = counts.get(role, 0)
        counts[role] = i + 1
        name = f"{role}{i}"
        x, pub = eg.keygen(rng)
        secrets_of[name] = x
        data = None
        if role == "dp":
            data = rng.integers(0, 10, size=(8,)).astype(np.int64)
            datas[name] = data
        n = DrynxNode(name, x, pub, data=data,
                      db_path=str(tmp_path / f"{name}.db"), policy=policy)
        n.start()
        entries.append(RosterEntry(name=name, role=role, host=n.address[0],
                                   port=n.address[1], public=pub))
        nodes.append(n)
    return nodes, entries, datas, secrets_of


def _stop(nodes):
    for n in nodes:
        n.stop()


def _frame(payload: bytes) -> bytes:
    return len(payload).to_bytes(4, "big") + payload


# -- RetryPolicy / FaultPlan units ------------------------------------------

def test_retry_policy_backoff_deterministic_and_capped():
    pol = RetryPolicy(backoff_s=0.2, backoff_cap_s=1.0, jitter=0.0)
    assert [pol.backoff(a) for a in range(4)] == [0.2, 0.4, 0.8, 1.0]
    j1 = RetryPolicy(backoff_s=0.2, backoff_cap_s=1.0, jitter=0.25, seed=3)
    j2 = RetryPolicy(backoff_s=0.2, backoff_cap_s=1.0, jitter=0.25, seed=3)
    draws = [j1.backoff(a) for a in range(4)]
    assert draws == [j2.backoff(a) for a in range(4)]  # seeded => replayable
    for a, d in enumerate(draws):
        base = min(0.2 * 2.0 ** a, 1.0)
        assert base * 0.75 <= d <= base * 1.25


def test_retry_policy_idempotency_gate():
    assert is_idempotent("ping") and is_idempotent("vn_bitmap")
    assert not is_idempotent("survey_dp") and not is_idempotent("made_up")
    pol = RetryPolicy(connect_retries=2)
    # connect-class failures (nothing sent) always retry
    assert pol.attempts_for("survey_dp", sent=False) == 3
    # idempotent calls retry even after a partial exchange
    assert pol.attempts_for("ping", sent=True) == 3
    # contributions never re-send once bytes hit the wire
    assert pol.attempts_for("survey_dp", sent=True) == 1


def test_fault_spec_validation():
    with pytest.raises(ValueError):
        FaultSpec(where="bogus", kind="drop")
    with pytest.raises(ValueError):
        FaultSpec(where="request", kind="bogus")
    with pytest.raises(ValueError):
        FaultSpec(where="request", kind="kill")  # node-level kind


def test_fault_plan_same_seed_same_draws():
    def draws(seed):
        plan = FaultPlan(seed=seed)
        plan.add(FaultSpec(where="request", kind="drop", prob=0.5))
        return [plan.pick("request", "dp0", "survey_dp") is not None
                for _ in range(32)]

    seq = draws(seed=9)
    assert seq == draws(seed=9)
    assert True in seq and False in seq  # prob actually gates

    def verdicts(seed):
        plan = FaultPlan(seed=seed)
        plan.add(FaultSpec(where="node", kind="kill", target="dp*",
                           prob=0.5))
        v = {f"dp{i}": plan.killed(f"dp{i}") for i in range(8)}
        # memoized: a node never flaps between dead and alive
        assert all(plan.killed(n) == dead for n, dead in v.items())
        return v

    assert verdicts(seed=4) == verdicts(seed=4)


def test_fault_plan_count_cap():
    plan = FaultPlan(seed=0)
    spec = plan.add(FaultSpec(where="connect", kind="refuse", target="dp0",
                              count=2))
    hits = [plan.pick("connect", "dp0") is not None for _ in range(4)]
    assert hits == [True, True, False, False] and spec.fired == 2


# -- fault episodes: windows, partitions, timelines (PR 17) ------------------

def test_fault_window_kill_heals_on_schedule():
    t = {"now": 0.0}
    clock = lambda: t["now"]  # noqa: E731

    def timeline(seed):
        t["now"] = 0.0
        plan = FaultPlan(seed=seed, clock=clock)
        plan.add(FaultSpec(where="node", kind="kill", target="dp0",
                           after_s=1.0, heal_after_s=2.0))
        out = []
        for now in (0.0, 0.5, 1.0, 2.9, 3.0, 5.0):
            t["now"] = now
            out.append(plan.killed("dp0"))
        return out

    # down exactly on [after_s, after_s + heal_after_s), replayable
    assert timeline(0) == [False, False, True, True, False, False]
    assert timeline(0) == timeline(0)

    # explicit kill with a heal window self-revives on schedule too
    t["now"] = 0.0
    plan = FaultPlan(seed=0, clock=clock)
    plan.kill("dp1", heal_after_s=1.5)
    assert plan.killed("dp1")
    t["now"] = 1.49
    assert plan.killed("dp1")
    t["now"] = 1.5
    assert not plan.killed("dp1")
    # heal-less kill stays the legacy permanent fault
    plan.kill("dp2")
    t["now"] = 1e9
    assert plan.killed("dp2")


def test_partition_window_symmetric_and_deterministic():
    t = {"now": 0.0}
    clock = lambda: t["now"]  # noqa: E731

    def verdicts(seed):
        t["now"] = 0.0
        plan = FaultPlan(seed=seed, clock=clock)
        plan.add(FaultSpec(where="node", kind="partition", target="cn*",
                           peer="dp*", prob=0.5, heal_after_s=4.0))
        t["now"] = 1.0
        v = {(a, b): plan.partitioned(a, b)
             for a in ("cn0", "cn1") for b in ("dp0", "dp1", "dp2")}
        # bidirectional: the cut reads the same from either end
        for (a, b), cut in v.items():
            assert plan.partitioned(b, a) == cut
        # links outside target x peer, and self-links, are never cut
        assert not plan.partitioned("dp0", "dp1")
        assert not plan.partitioned("cn0", "cn0")
        t["now"] = 4.0   # window elapsed: every cut link heals
        assert not any(plan.partitioned(a, b) for (a, b) in v)
        return v

    v = verdicts(7)
    assert v == verdicts(7)   # same seed => same blast radius
    assert True in v.values() and False in v.values()


def test_fault_plan_episodes_timeline():
    def rows(seed):
        plan = FaultPlan(seed=seed, clock=lambda: 0.0)
        plan.add(FaultSpec(where="node", kind="kill", target="dp1",
                           after_s=0.5, heal_after_s=1.0))
        plan.add(FaultSpec(where="node", kind="partition", target="cn*",
                           peer="dp*", after_s=2.0, heal_after_s=3.0))
        plan.kill("vn0", heal_after_s=4.0)
        return plan.episodes()

    r = rows(3)
    assert r == rows(3)       # the soak harness diffs this across runs
    assert r[0] == {"spec": 0, "kind": "kill", "target": "dp1",
                    "peer": None, "down_s": 0.5, "heal_s": 1.5}
    assert r[1] == {"spec": 1, "kind": "partition", "target": "cn*",
                    "peer": "dp*", "down_s": 2.0, "heal_s": 5.0}
    assert r[2] == {"spec": None, "kind": "kill", "target": "vn0",
                    "peer": None, "down_s": 0.0, "heal_s": 4.0}


def test_fault_window_validation():
    with pytest.raises(ValueError):
        FaultSpec(where="node", kind="kill", heal_after_s=0.0)
    with pytest.raises(ValueError):
        FaultSpec(where="request", kind="drop", heal_after_s=1.0)
    with pytest.raises(ValueError):
        FaultSpec(where="request", kind="partition")  # node-level kind


# -- framing hardening (satellite 1) ----------------------------------------

def test_recv_msg_bounds_frame_length():
    a, b = socket.socketpair()
    try:
        a.sendall((2048).to_bytes(4, "big"))  # header only; no 2 KiB body
        with pytest.raises(FrameTooLarge, match="2048"):
            recv_msg(b, max_bytes=1024)
    finally:
        a.close()
        b.close()


def test_recv_msg_rejects_corrupt_frame():
    a, b = socket.socketpair()
    try:
        a.sendall(_frame(b"\xff{not json"))
        with pytest.raises(CorruptFrame):
            recv_msg(b)
        a.sendall(_frame(b'{"type": "ok"}'))
        assert recv_msg(b) == {"type": "ok"}
    finally:
        a.close()
        b.close()


def test_call_timeout_marks_connection_broken():
    srv = NodeServer()
    srv.register("nap", lambda m: time.sleep(m["s"]) or {"ok": True})
    srv.start()
    c = Conn(srv.host, srv.port, timeout=0.3)
    try:
        with pytest.raises(CallTimeout):
            c.call({"type": "nap", "s": 5.0})
        # a timed-out stream is poisoned: later calls must refuse upfront
        with pytest.raises(ConnectionClosed):
            c.call({"type": "nap", "s": 0.0})
        assert isinstance(CallTimeout("x"), TimeoutError)  # typed hierarchy
        assert issubclass(CallTimeout, TransportError)
    finally:
        c.close()
        srv.stop()


# -- retry semantics over real sockets --------------------------------------

def test_call_entry_retries_refused_connect():
    srv = NodeServer()
    calls = []
    srv.register("ping", lambda m: calls.append(1) or {"ok": True})
    srv.start()
    entry = RosterEntry(name="p0", role="dp", host=srv.host, port=srv.port,
                        public=(0, 0))
    plan = FaultPlan(seed=0)
    plan.add(FaultSpec(where="connect", kind="refuse", target="p0", count=2))
    set_fault_plan(plan)
    try:
        with pytest.raises(ConnectError):      # no retries -> surfaces
            call_entry(entry, {"type": "ping"}, retries=0, policy=FAST)
        # one fault charge left; a single retry rides past it
        assert call_entry(entry, {"type": "ping"}, retries=1,
                          policy=FAST)["ok"]
        assert calls == [1]
    finally:
        srv.stop()


def test_idempotent_call_retried_after_dropped_request():
    srv = NodeServer()
    calls = []
    srv.register("ping", lambda m: calls.append(1) or {"ok": True})
    srv.start()
    entry = RosterEntry(name="p1", role="dp", host=srv.host, port=srv.port,
                        public=(0, 0))
    plan = FaultPlan(seed=0)
    plan.add(FaultSpec(where="request", kind="drop", target="p1",
                       mtype="ping", count=1))
    set_fault_plan(plan)
    pol = RetryPolicy(connect_retries=2, backoff_s=0.01, backoff_cap_s=0.02,
                      jitter=0.0, call_timeout_s=0.4, seed=0)
    try:
        # the dropped frame costs one call-timeout, then the idempotent
        # retry goes through on a fresh connection
        assert call_entry(entry, {"type": "ping"}, policy=pol)["ok"]
        assert calls == [1]
    finally:
        srv.stop()


def test_contribution_never_resent_after_partial_write():
    srv = NodeServer()
    calls = []
    srv.register("survey_dp", lambda m: calls.append(1) or {"ok": True})
    srv.start()
    entry = RosterEntry(name="p2", role="dp", host=srv.host, port=srv.port,
                        public=(0, 0))
    plan = FaultPlan(seed=0)
    plan.add(FaultSpec(where="request", kind="close_mid_frame", target="p2",
                       mtype="survey_dp"))
    set_fault_plan(plan)
    pol = RetryPolicy(connect_retries=5, backoff_s=0.01, backoff_cap_s=0.02,
                      jitter=0.0, call_timeout_s=0.4, seed=0)
    try:
        with pytest.raises(ConnectionClosed, match="partial write"):
            call_entry(entry, {"type": "survey_dp"}, policy=pol)
        # the torn frame never reached the handler, and despite 5 allowed
        # connect retries the contribution was NOT re-sent
        assert calls == []
    finally:
        srv.stop()


# -- quorum-degraded surveys over TCP ---------------------------------------

def test_survey_quorum_degraded_dp_dead_at_dispatch(tmp_path):
    rng = np.random.default_rng(101)
    nodes, entries, datas, _ = _boot(
        tmp_path, ["cn", "dp", "dp", "dp", "dp", "dp"], rng)
    try:
        client = RemoteClient(Roster(entries), rng, policy=FAST)
        client.broadcast_roster()
        plan = FaultPlan(seed=1)
        plan.kill("dp1")
        set_fault_plan(plan)
        result = client.run_survey("sum", query_min=0, query_max=9,
                                   survey_id="sv-quorum",
                                   dlog=eg.DecryptionTable(limit=500),
                                   min_dp_quorum=4)
        want = int(sum(d.sum() for n, d in datas.items() if n != "dp1"))
        assert result == want
        assert client.last_responders == ["dp0", "dp2", "dp3", "dp4"]
        assert client.last_absent == ["dp1"]
        # strict mode (quorum 0 = all DPs) must refuse the same roster
        with pytest.raises(RemoteError, match="responded"):
            client.run_survey("sum", query_min=0, query_max=9,
                              survey_id="sv-strict",
                              dlog=eg.DecryptionTable(limit=500))
    finally:
        _stop(nodes)


def test_survey_dp_dies_mid_contribution(tmp_path):
    """The DP's reply is torn mid-frame AFTER its handler ran: the root
    never re-sends the torn call (idempotency contract), but the healing
    re-entry pass (PR 17) re-probes, finds the DP answering, and
    re-dispatches it as NEW sub-work — the reply cache replays the very
    ciphertext bytes the torn frame hid, so the survey completes over
    the FULL roster with the contribution counted exactly once."""
    rng = np.random.default_rng(102)
    nodes, entries, datas, _ = _boot(
        tmp_path, ["cn", "dp", "dp", "dp", "dp", "dp"], rng)
    try:
        client = RemoteClient(Roster(entries), rng, policy=FAST)
        client.broadcast_roster()
        plan = FaultPlan(seed=2)
        plan.add(FaultSpec(where="reply", kind="close_mid_frame",
                           target="dp2", mtype="survey_dp", count=1))
        set_fault_plan(plan)
        result = client.run_survey("sum", query_min=0, query_max=9,
                                   survey_id="sv-midc",
                                   dlog=eg.DecryptionTable(limit=500),
                                   min_dp_quorum=4)
        # exactly once: the full sum, not full + dp2 again
        assert result == int(sum(d.sum() for d in datas.values()))
        assert client.last_responders == ["dp0", "dp1", "dp2", "dp3",
                                          "dp4"]
        assert client.last_absent == []
        # the root re-entered collect from its checkpoint, not restarted
        assert client.last_phases.get("collect", 0) >= 2
    finally:
        _stop(nodes)


def test_survey_seeded_chaos_is_deterministic(tmp_path):
    """Acceptance bar: the same FaultPlan seed yields the same responder
    set AND the same degraded aggregate across two runs.

    Uses node-level kills (memoized never-flap verdicts, no heal
    window) rather than per-draw connect refusals: the healing collect
    re-entry legitimately revives a DP whose transient refusal clears
    on re-probe, so only a permanent verdict keeps the membership
    deterministically degraded."""
    rng = np.random.default_rng(103)
    nodes, entries, datas, _ = _boot(
        tmp_path, ["cn", "dp", "dp", "dp", "dp", "dp"], rng)
    try:
        client = RemoteClient(Roster(entries), rng, policy=FAST)
        client.broadcast_roster()

        def chaos_run(survey_id):
            plan = FaultPlan(seed=12)
            plan.add(FaultSpec(where="node", kind="kill",
                               target="dp*", prob=0.5))
            set_fault_plan(plan)
            pol = RetryPolicy(connect_retries=0, backoff_s=0.01,
                              backoff_cap_s=0.02, jitter=0.0,
                              call_timeout_s=rp.CALL_TIMEOUT_S, seed=0)
            for n in nodes:
                n.policy = pol        # one kill draw per DP, memoized
            result = client.run_survey("sum", query_min=0, query_max=9,
                                       survey_id=survey_id,
                                       dlog=eg.DecryptionTable(limit=500),
                                       min_dp_quorum=1)
            return result, list(client.last_responders), \
                list(client.last_absent)

        r1, resp1, abs1 = chaos_run("sv-det-a")
        r2, resp2, abs2 = chaos_run("sv-det-b")
        assert (r1, resp1, abs1) == (r2, resp2, abs2)
        assert 1 <= len(resp1) < 5      # the coin actually fired
        assert int(r1) == int(sum(datas[n].sum() for n in resp1))
    finally:
        _stop(nodes)


def test_survey_heals_through_partition_window(tmp_path):
    """A live partition cuts cn0 <-> dp1 at dispatch; the link heals
    inside the survey's bounded re-entry budget (CHECKPOINT_MAX_RESUMES
    passes spaced RESUME_BACKOFF_S apart), so the root's healing pass
    re-probes, re-dispatches dp1, and the survey completes over the FULL
    roster — partition tolerance, not just degradation."""
    rng = np.random.default_rng(108)
    nodes, entries, datas, _ = _boot(tmp_path, ["cn", "dp", "dp", "dp"],
                                     rng)
    try:
        client = RemoteClient(Roster(entries), rng, policy=FAST)
        client.broadcast_roster()
        plan = FaultPlan(seed=5)
        plan.add(FaultSpec(where="node", kind="partition", target="cn0",
                           peer="dp1", heal_after_s=0.7))
        set_fault_plan(plan)
        result = client.run_survey("sum", query_min=0, query_max=9,
                                   survey_id="sv-part-heal",
                                   dlog=eg.DecryptionTable(limit=500),
                                   min_dp_quorum=2)
        assert result == int(sum(d.sum() for d in datas.values()))
        assert client.last_responders == ["dp0", "dp1", "dp2"]
        assert client.last_absent == []
        # healed via checkpoint re-entry, not a clean first pass
        assert client.last_phases.get("collect", 0) >= 2
    finally:
        _stop(nodes)


def test_dp_reply_cache_replays_across_revival(tmp_path, monkeypatch):
    """Satellite 4: a DP dies AFTER contributing (handler ran, proof
    fired, reply torn), stays unreachable for a window, revives, and is
    re-dispatched by the healing pass. The contribution must be computed
    exactly once (fresh blinding entropy means a recompute could NOT be
    byte-identical — replay identity comes only from the reply cache)
    and its range proof must fire at the VNs exactly once."""
    monkeypatch.setenv("DRYNX_TOPOLOGY", "star")
    rng = np.random.default_rng(109)
    roles = ["cn", "dp", "dp", "dp", "vn"]
    nodes, entries, datas, _ = _boot(tmp_path, roles, rng, policy=None)
    dp1 = next(n for n in nodes if n.name == "dp1")
    computes, fires = [], []
    orig_contrib = dp1._dp_contribution
    orig_fire = dp1._fire_proof_request_async
    dp1._dp_contribution = lambda m: (computes.append(m["survey_id"]),
                                      orig_contrib(m))[1]
    dp1._fire_proof_request_async = lambda r: (fires.append(r.differ_info),
                                               orig_fire(r))[1]
    try:
        client = RemoteClient(Roster(entries), rng)
        client.broadcast_roster()
        plan = FaultPlan(seed=6)
        # dp1's reply is torn after its handler ran ("dies after
        # contributing"), then the node refuses the next two dials
        # (down for a window) before reviving
        plan.add(FaultSpec(where="reply", kind="close_mid_frame",
                           target="dp1", mtype="survey_dp", count=1))
        plan.add(FaultSpec(where="connect", kind="refuse", target="dp1",
                           count=2))
        set_fault_plan(plan)
        result, block = client.run_survey(
            "sum", query_min=0, query_max=9, proofs=True, ranges=[(4, 4)],
            survey_id="sv-replay", dlog=eg.DecryptionTable(limit=500),
            timeout=rp.COLD_COMPILE_WAIT_S, min_dp_quorum=2)
        # counted exactly once, full roster
        assert result == int(sum(d.sum() for d in datas.values()))
        assert client.last_responders == ["dp0", "dp1", "dp2"]
        assert client.last_phases.get("collect", 0) >= 2
        # computed once, replayed from the cache on re-dispatch
        assert computes.count("sv-replay") == 1
        # the proof fired at the VNs exactly once despite two dispatches
        assert fires == ["range-dp1"]
        dp1_keys = [k for k in block["bitmap"]
                    if k.endswith("/range-dp1")]
        assert len(dp1_keys) == 1          # one VN, one entry: fired once
        assert block["bitmap"][dp1_keys[0]] == 1
    finally:
        _stop(nodes)


def test_probe_liveness_skips_dead_roster_entries(tmp_path):
    rng = np.random.default_rng(104)
    nodes, entries, datas, _ = _boot(tmp_path, ["cn", "dp", "dp"], rng)
    # a roster entry nothing listens on: allocate a port, then free it
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    entries = entries + [RosterEntry(name="dp2", role="dp",
                                     host="127.0.0.1", port=dead_port,
                                     public=entries[0].public)]
    try:
        client = RemoteClient(Roster(entries), rng, policy=FAST)
        # the dead entry must not abort the broadcast
        assert client.broadcast_roster() == {"cn0": True, "dp0": True,
                                             "dp1": True, "dp2": False}
        alive = client.probe_liveness()
        assert alive == {"cn0": True, "dp0": True, "dp1": True,
                         "dp2": False}
        # probe=True excludes the dead DP before dispatch instead of
        # paying a connect failure for it inside the survey
        result = client.run_survey("sum", query_min=0, query_max=9,
                                   survey_id="sv-probe",
                                   dlog=eg.DecryptionTable(limit=500),
                                   min_dp_quorum=2, probe=True)
        assert result == int(sum(d.sum() for d in datas.values()))
        assert client.last_responders == ["dp0", "dp1"]
        assert client.last_absent == ["dp2"]
    finally:
        _stop(nodes)


# -- VN quorum --------------------------------------------------------------

def _proof_request_msg(req):
    def pack_bytes(b):
        return pack_array(np.frombuffer(b, dtype=np.uint8))

    return {"type": "proof_request", "proof_type": req.proof_type,
            "survey_id": req.survey_id, "sender_id": req.sender_id,
            "differ_info": req.differ_info, "round_id": req.round_id,
            "data": pack_bytes(req.data),
            "signature": pack_bytes(req.signature.to_bytes())}


def test_end_verification_vn_quorum(tmp_path):
    """3 VNs expect one proof each; only 2 receive it. Strict
    end_verification refuses; vn_quorum=2/3 commits and records the
    straggler."""
    from drynx_tpu.proofs import requests as rq

    rng = np.random.default_rng(105)
    nodes, entries, _, secrets_of = _boot(
        tmp_path, ["cn", "vn", "vn", "vn"], rng)
    try:
        client = RemoteClient(Roster(entries), rng, policy=FAST)
        client.broadcast_roster()
        vns = [e for e in entries if e.role == "vn"]
        for e in vns:
            call_entry(e, {"type": "vn_register", "survey_id": "sv-vnq",
                           "expected": 1, "proofs": False}, policy=FAST)
        req = rq.new_proof_request("range", "sv-vnq", "cn0", "dp0", 0,
                                   b"payload", secrets_of["cn0"])
        for e in vns[:2]:                      # vn2 never gets its proof
            call_entry(e, _proof_request_msg(req), policy=FAST)

        with pytest.raises(RemoteError, match="complete bitmaps"):
            call_entry(vns[0], {"type": "end_verification",
                                "survey_id": "sv-vnq", "timeout": 1.0,
                                "vn_quorum": 1.0},
                       timeout=30.0, policy=FAST)
        block = call_entry(vns[0], {"type": "end_verification",
                                    "survey_id": "sv-vnq", "timeout": 5.0,
                                    "vn_quorum": 2 / 3},
                           timeout=30.0, policy=FAST)
        assert block["vn_reported"] == ["vn0", "vn1"]
        assert block["vn_absent"] == ["vn2"]
        assert {k.split(":")[0] for k in block["bitmap"]} == {"vn0", "vn1"}
    finally:
        _stop(nodes)


def test_vn_adjust_expected_retriggers_range_flush(tmp_path):
    """A VN holding buffered range payloads flushes the joint check as
    soon as the root shrinks the expected-proof counters to the responder
    set — otherwise an absent DP stalls the survey forever."""
    from drynx_tpu.proofs import requests as rq
    from drynx_tpu.service.proof_collection import VerifyingNode

    rng = np.random.default_rng(106)
    x, pub = eg.keygen(rng)
    flushes = []

    def joint(datas, sid):
        flushes.append(len(datas))
        return [True] * len(datas)

    vn = VerifyingNode("vn0", str(tmp_path / "vn.db"), {"cn0": pub},
                       verify_fns={"range_joint": joint})
    vn.register_survey("sv-adj", 3, {"range": 1.0}, expected_range=3)
    for i in range(2):
        req = rq.new_proof_request("range", "sv-adj", "cn0", f"dp{i}", 0,
                                   b"payload-%d" % i, x)
        assert vn.receive_proof(req) == rq.BM_RECVD
    st = vn.surveys["sv-adj"]
    assert flushes == [] and not st.done.is_set()

    vn.adjust_expected("sv-adj", 1, expected_range=2)
    assert flushes == [2]                       # flush fired on the adjust
    assert st.done.is_set()
    assert sorted(st.bitmap.values()) == [rq.BM_TRUE, rq.BM_TRUE]


# -- full pipeline acceptance (proofs on) -----------------------------------

@pytest.mark.slow
def test_e2e_survey_dead_dp_and_straggling_vn(tmp_path):
    """ISSUE acceptance: 1/5 DPs dead and 1/3 VNs unreachable; the survey
    completes within the quorum path with the correct aggregate over the 4
    responding DPs and an audit block carried by the 2 live VNs."""
    from drynx_tpu.proofs import requests as rq

    rng = np.random.default_rng(107)
    roles = ["cn", "cn"] + ["dp"] * 5 + ["vn"] * 3
    nodes, entries, datas, _ = _boot(tmp_path, roles, rng, policy=None)
    try:
        client = RemoteClient(Roster(entries), rng)
        client.broadcast_roster()
        plan = FaultPlan(seed=42)
        plan.kill("dp4")
        plan.kill("vn2")
        set_fault_plan(plan)
        result, block = client.run_survey(
            "sum", query_min=0, query_max=9, proofs=True, ranges=[(4, 4)],
            survey_id="sv-chaos-e2e", dlog=eg.DecryptionTable(limit=500),
            timeout=rp.COLD_COMPILE_WAIT_S, min_dp_quorum=4,
            vn_quorum=2 / 3, probe=True)

        want = int(sum(d.sum() for n, d in datas.items() if n != "dp4"))
        assert result == want
        assert client.last_responders == ["dp0", "dp1", "dp2", "dp3"]
        assert client.last_absent == ["dp4"]

        assert block["vn_reported"] == ["vn0", "vn1"]
        assert block["vn_absent"] == ["vn2"]
        # 4 range + 1 aggregation + 2 keyswitch per live VN, all verified
        bitmap = block["bitmap"]
        assert len(bitmap) == 7 * 2, sorted(bitmap)
        assert set(bitmap.values()) == {rq.BM_TRUE}, bitmap
        assert {k.split(":")[0] for k in bitmap} == {"vn0", "vn1"}
    finally:
        _stop(nodes)
