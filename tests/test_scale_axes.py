"""Reference-scale axes (PR 8): the bucket-tile planner's memory bound,
tiled-vs-monolithic bit identity for the grid encoders and the range-proof
transcripts, chunked-vs-unchunked DRO byte identity, the vectorized noise
generator against its loop reference, sparse-grid decode semantics, and
the scale-bench supervisor's per-point outcome labeling (stub children).

Fast by default: only the two crypto round-trip tests compile kernels and
carry the `slow` mark."""
import importlib.util
import json
import os
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from drynx_tpu.encoding import stats as st  # noqa: E402
from drynx_tpu.encoding import tiles  # noqa: E402

PY = sys.executable


def _scale_mod():
    spec = importlib.util.spec_from_file_location(
        "bench_scale_axes",
        os.path.join(ROOT, "scripts", "bench_scale_axes.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# Tile planner: balance, coverage, and the 65k-bucket memory bound
# ---------------------------------------------------------------------------

def test_plan_tiles_balanced_and_covering():
    for n in (1, 5, 4096, 4097, 8193, 65536, 1_000_000):
        plan = tiles.plan_tiles(n, 4096)
        assert plan.covers(), n
        widths = {b - a for a, b in plan.tiles}
        assert max(widths) <= 4096
        assert max(widths) - min(widths) <= 1, (n, widths)
        assert plan.n_tiles == -(-n // 4096)


def test_plan_tiles_monolithic_cases():
    assert tiles.plan_tiles(100, 0).tiles == ((0, 100),)
    assert tiles.plan_tiles(100, 200).tiles == ((0, 100),)
    assert tiles.plan_tiles(0, 4096).tiles == ()


def test_65k_bucket_peak_mask_bounded_by_tile():
    """The acceptance bound: at 65536 buckets the largest row-by-grid
    mask any single tiled encode dispatch materializes is rows x tile,
    NOT rows x buckets."""
    R, rows = 65536, 600
    t = tiles.auto_tile(R)
    assert t == tiles.tile_width()          # tiling is the DEFAULT here
    plan = tiles.plan_tiles(R, t)
    assert plan.covers()
    assert plan.max_tile_width <= tiles.tile_width()
    assert plan.peak_mask_elems(rows) == rows * plan.max_tile_width
    assert plan.peak_mask_elems(rows) <= rows * tiles.DEFAULT_TILE
    assert plan.peak_mask_elems(rows) < rows * R / 10


def test_auto_tile_policy_and_env_override(monkeypatch):
    assert tiles.auto_tile(tiles.TILE_THRESHOLD) == 0
    assert tiles.auto_tile(tiles.TILE_THRESHOLD + 1) == tiles.DEFAULT_TILE
    monkeypatch.setenv(tiles.ENV_TILE, "512")
    assert tiles.tile_width() == 512
    assert tiles.auto_tile(tiles.TILE_THRESHOLD + 1) == 512
    monkeypatch.setenv(tiles.ENV_TILE, "garbage")
    assert tiles.tile_width() == tiles.DEFAULT_TILE


def test_proof_tile_shards():
    assert tiles.proof_tile_shards(100, 0) == 1
    assert tiles.proof_tile_shards(100, 200) == 1
    assert tiles.proof_tile_shards(4097, 4096) == 2
    assert tiles.proof_tile_shards(65536, 4096) == 16


# ---------------------------------------------------------------------------
# Tiled encode: bit-identical to the monolithic grid encoders
# ---------------------------------------------------------------------------

GRID_CASES = [(op, rows, R) for op in st.GRID_OPS
              for rows, R in ((50, 300), (7, 64))]


@pytest.mark.parametrize("op,rows,R", GRID_CASES)
def test_tiled_encode_bit_identical(op, rows, R):
    rng = np.random.default_rng(3)
    data = rng.integers(0, R, rows)
    mono = np.asarray(st.encode_clear(op, data, 0, R - 1))  # below
    # threshold -> the dense monolithic path
    tiled = np.asarray(st.encode_clear_tiled(op, data, 0, R - 1, tile=33))
    assert np.array_equal(mono, tiled), op


def test_encode_clear_auto_tiles_above_threshold():
    """Above TILE_THRESHOLD encode_clear dispatches the tiled path by
    default, and the result equals a single-tile (monolithic) pass."""
    R = tiles.TILE_THRESHOLD + 5
    rng = np.random.default_rng(4)
    data = rng.integers(0, R, 40)
    auto = np.asarray(st.encode_clear("min", data, 0, R - 1))
    one_tile = np.asarray(
        st.encode_clear_tiled("min", data, 0, R - 1, tile=R))
    assert np.array_equal(auto, one_tile)
    assert auto.shape == (R,)


def test_encode_clear_tiles_offsets_partition():
    offs = [(off, np.asarray(enc).shape[0]) for off, enc
            in st.encode_clear_tiles("union", np.asarray([1, 2]), 0, 99,
                                     tile=16)]
    pos = 0
    for off, w in offs:
        assert off == pos
        pos += w
    assert pos == 100


# ---------------------------------------------------------------------------
# Sparse-grid decode semantics (empty-group sentinels, max ambiguity)
# ---------------------------------------------------------------------------

def _dec(values):
    v = np.asarray(values, dtype=np.int64)
    return st.DecryptedVector(values=v, found=np.ones(v.shape, bool),
                              is_zero=(v == 0))


def test_decode_min_max_large_sparse_grid():
    R, lo, hit = 65536, 10, 12345
    v = np.zeros(R, dtype=np.int64)
    v[hit:] = 1                       # min: OR bits from the min upward
    assert st.decode("min", _dec(v), lo, lo + R - 1) == lo + hit
    c = np.zeros(R, dtype=np.int64)
    c[:hit] = 1                       # max: complement bits below the max
    assert st.decode("max", _dec(c), lo, lo + R - 1) == lo + hit


def test_decode_min_empty_is_none_max_empty_is_query_min():
    """No data: min's all-zero OR bits decode to the None sentinel; max's
    AND-complement neutral element is indistinguishable from a genuine
    max of query_min (the documented reference ambiguity)."""
    z = np.zeros(100, dtype=np.int64)
    assert st.decode("min", _dec(z), 7, 106) is None
    assert st.decode("max", _dec(z), 7, 106) == 7


def test_decode_union_inter_frequency_sparse():
    v = np.zeros(1000, dtype=np.int64)
    v[[3, 997]] = 2
    assert st.decode("union", _dec(v), 5, 1004) == [8, 1002]
    inter = st.decode("inter", _dec(v), 5, 1004)
    assert 8 not in inter and 1002 not in inter and len(inter) == 998
    freq = st.decode("frequency_count", _dec(v), 5, 1004)
    assert freq[8] == 2 and freq[9] == 0 and len(freq) == 1000


def test_decode_grouped_empty_group_sentinels():
    R, gvals = 64, [(), ()]
    g0 = np.zeros(R, dtype=np.int64)
    g0[20:] = 1
    g1 = np.zeros(R, dtype=np.int64)  # empty group
    vec = _dec(np.concatenate([g0, g1]))
    grid = np.asarray([[0], [1]])
    out = st.decode_grouped("min", vec, grid, 0, R - 1)
    assert out[(0,)] == 20 and out[(1,)] is None
    out = st.decode_grouped("max", vec, grid, 0, R - 1)
    # g0's complement encoding is all-zero-above -> decodes to 0 here;
    # the empty group hits the documented query_min ambiguity
    assert out[(1,)] == 0


# ---------------------------------------------------------------------------
# Vectorized noise generation == loop reference (golden)
# ---------------------------------------------------------------------------

NOISE_CASES = [
    (100, 0.0, 30.0, 100.0, 1.0, 0.0),
    (1000, 0.0, 30.0, 100.0, 1.0, 0.0),
    (512, 5.0, 2.0, 10.0, 1.0, 0.0),
    (256, -3.0, 1.0, 1.0, 2.0, 0.0),       # sharp density
    (300, 0.0, 50.0, 0.5, 1.0, 0.0),        # tiny quanta
    (200, 0.0, 30.0, 100.0, 1.0, 400.0),    # aggressive limit
    (1, 0.0, 30.0, 100.0, 1.0, 0.0),
    (10000, 1.5, 12.0, 7.0, 0.5, 0.0),
]


@pytest.mark.parametrize("size,mean,b,quanta,scale,limit", NOISE_CASES)
def test_noise_values_match_loop_reference(size, mean, b, quanta, scale,
                                           limit):
    from drynx_tpu.parallel import dro

    got = dro.generate_noise_values(size, mean, b, quanta, scale, limit)
    want = dro._generate_noise_values_ref(size, mean, b, quanta, scale,
                                          limit)
    assert got.dtype == want.dtype
    assert np.array_equal(got, want)


def test_noise_values_order_and_size():
    from drynx_tpu.parallel import dro

    out = dro.generate_noise_values(7, 0.0, 30.0, 100.0)
    assert len(out) == 7
    # order is [m, m+q, m-q, m+2q, m-2q, ...] expanded by repetition
    assert out[0] == 0


# ---------------------------------------------------------------------------
# DRO API convention: FixedBase at the encryption boundary, raw tables in
# the shuffle layer — mixing them is a TypeError, not a silent reshape
# ---------------------------------------------------------------------------

def test_dro_table_convention_typeerrors():
    from drynx_tpu.crypto import elgamal as eg
    from drynx_tpu.parallel import dro

    fb = eg.BASE_TABLE                  # FixedBase wrapper
    raw = eg.BASE_TABLE.table
    with pytest.raises(TypeError):
        dro.encrypt_noise(None, raw, None)
    with pytest.raises(TypeError):
        dro.precompute_rerandomization(None, fb, 4)
    with pytest.raises(TypeError):
        dro.shuffle_rerandomize(None, None, fb)
    with pytest.raises(TypeError):
        dro.dro_pipeline(None, raw, 4, 0.0, 30.0, 100.0)


# ---------------------------------------------------------------------------
# Scale-bench supervisor: per-point labeling (stub children, jax-free)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def scale():
    return _scale_mod()


def test_point_result_ok_complete(scale):
    rec = {"stage": "complete", "encode_cold_s": 1.2}
    pt = scale.point_result("minmax", 65536, "ok", 0, 12.34, rec)
    assert pt["status"] == "ok" and pt["axis"] == "minmax"
    assert pt["n"] == 65536 and pt["encode_cold_s"] == 1.2
    assert "stage" not in pt


def test_point_result_failure_labels(scale):
    cases = [("ok", 0, {}, "child_exited_without_record"),
             ("rc:2", 2, {"stage": "encode"}, "failed_rc2"),
             ("signal:SIGSEGV", -11, {"stage": "prove"},
              "killed_sigsegv"),
             ("timeout", None, {"stage": "encrypt"}, "timeout")]
    for outcome, rc, rec, want in cases:
        pt = scale.point_result("dro", 10, outcome, rc, 1.0, rec)
        assert pt["status"] == want, outcome
        assert pt["last_stage"] == rec.get("stage", "none")


def test_skip_result_records_reason(scale):
    pt = scale.skip_result("rows", 600000, "cpu: beyond budget")
    assert pt["status"] == "skipped" and pt["reason"]


def test_point_result_with_real_stub_children(scale, tmp_path):
    """Drive actual child processes through the supervisor: a clean child
    that writes a complete record, a crasher, and a hang."""
    import bench

    rec = str(tmp_path / "rec.json")
    prog = ("import json,sys; json.dump({'stage':'complete','x':1}, "
            "open(sys.argv[1],'w'))")
    out, rc, el, _ = bench.supervise_child([PY, "-c", prog, rec], 30)
    pt = scale.point_result("minmax", 1, out, rc, el,
                            bench.read_record(rec))
    assert pt["status"] == "ok" and pt["x"] == 1

    out, rc, el, _ = bench.supervise_child(
        [PY, "-c", "import os,signal;os.kill(os.getpid(),signal.SIGKILL)"],
        30)
    pt = scale.point_result("minmax", 1, out, rc, el, {})
    assert pt["status"] == "killed_sigkill"

    out, rc, el, _ = bench.supervise_child(
        [PY, "-c", "import time;time.sleep(60)"], 0.5)
    pt = scale.point_result("dro", 1, out, rc, el, {})
    assert pt["status"] == "timeout" and el < 30


def test_progressive_record_atomic(scale, tmp_path):
    out = str(tmp_path / "BENCH.json")
    doc = {"points": [{"axis": "minmax", "n": 1, "status": "ok"}]}
    scale.write_progressive(out, doc)
    assert json.load(open(out)) == doc
    assert not os.path.exists(out + ".tmp")


def test_grids_cover_required_points(scale):
    """The acceptance floor for the CPU capture."""
    assert {1024, 4096, 16384, 65536} <= set(scale.GRIDS["minmax"])
    assert {600, 8192, 65536} <= set(scale.GRIDS["rows"])
    assert {10000, 100000} <= set(scale.GRIDS["dro"])
    for axis, pts in scale.SMOKE_GRIDS.items():
        cap = {"minmax": 256, "rows": 1024, "dro": 512}[axis]
        assert max(pts) <= cap


# ---------------------------------------------------------------------------
# Crypto round trips (compile-heavy -> slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_tiled_range_proof_transcript_byte_identical():
    """Forced tiling at small V: the Fiat-Shamir transcript (to_bytes)
    must be byte-equal to the monolithic path, and still verify."""
    import jax
    import jax.numpy as jnp

    from drynx_tpu.crypto import elgamal as eg
    from drynx_tpu.proofs import range_proof as rp

    rng = np.random.default_rng(7)
    U, L, V = 2, 1, 12
    sigs = [rp.init_range_sig(U, rng) for _ in range(2)]
    _, ca_pub = eg.keygen(rng)
    tbl = eg.pub_table(ca_pub)
    secrets = np.asarray(rng.integers(0, U, V), dtype=np.int64)
    cts, rs = eg.encrypt_ints(jax.random.PRNGKey(3), tbl,
                              jnp.asarray(secrets))
    mono = rp.create_range_proofs(jax.random.PRNGKey(5), secrets, rs, cts,
                                  sigs, U, L, tbl.table, tile=0,
                                  shard=False)
    tiled = rp.create_range_proofs(jax.random.PRNGKey(5), secrets, rs,
                                   cts, sigs, U, L, tbl.table, tile=5,
                                   shard=False)
    assert mono.to_bytes() == tiled.to_bytes()
    ok = rp.verify_range_proofs(tiled, [s.public for s in sigs], tbl.table)
    assert np.asarray(ok).all()


@pytest.mark.slow
def test_chunked_dro_byte_identical():
    """Chunked precompute + shuffle at a forced small chunk must be
    byte-identical to the monolithic path for the same key."""
    import jax
    import numpy as np

    from drynx_tpu.crypto import elgamal as eg
    from drynx_tpu.parallel import dro

    rng = np.random.default_rng(7)
    _, pub = eg.keygen(rng)
    tbl = eg.pub_table(pub)
    S = 8
    key = jax.random.PRNGKey(1)
    z_mono, r_mono = dro.precompute_rerandomization(key, tbl.table, S,
                                                    chunk=0)
    z_chnk, r_chnk = dro.precompute_rerandomization(key, tbl.table, S,
                                                    chunk=3)
    assert np.array_equal(np.asarray(r_mono), np.asarray(r_chnk))
    assert np.array_equal(np.asarray(z_mono), np.asarray(z_chnk))

    k2 = jax.random.PRNGKey(2)
    cts = z_mono  # any ciphertext pool works
    a, pa, ra = dro.shuffle_rerandomize(k2, cts, tbl.table,
                                        precomp=(z_mono, r_mono), chunk=0)
    b, pb, rb = dro.shuffle_rerandomize(k2, cts, tbl.table,
                                        precomp=(z_mono, r_mono), chunk=3)
    assert np.array_equal(np.asarray(pa), np.asarray(pb))
    assert np.array_equal(np.asarray(ra), np.asarray(rb))
    assert np.array_equal(np.asarray(a), np.asarray(b))
