"""The standing survey server (drynx_tpu/server): admission control,
cross-survey batched verification, the encode/verify pipeline.

Quick tier: registry contracts for the cross-survey (n_queue) program
set, admission triage over a stub cluster, scheduler mechanics
(grouping, bounded depth, neighbor isolation, the verify worker) with
the compile driver monkeypatched out, transcript determinism, and the
span-intersection overlap metric — no real surveys, no compiles.

Slow tier: one proofs-on end-to-end run asserting the headline
properties (batched-vs-serial byte-identical transcripts, compile-lane
admission, measured pipeline overlap, zero off-MainThread tracing) and
one FaultPlan soak (a killed DP degrades membership without poisoning
the queue's other surveys)."""
import dataclasses
import threading
import types

import numpy as np
import pytest

from drynx_tpu import compilecache as cc
from drynx_tpu.server import (AdmissionController, Overloaded, QueueFull,
                              QuotaExceeded, SurveyServer,
                              pipeline_overlap, survey_transcript,
                              transcript_digest)
from drynx_tpu.utils.timers import PhaseTimers


# -- registry: the cross-survey program set ----------------------------------

def test_registry_queue_program_set():
    """Profile.n_queue > 1 must only ever ADD programs — the concat
    buckets the batched verify dispatches — on the CrossSurvey phases,
    so admission folding n_queue into the profile certifies batching
    without losing single-survey AOT coverage."""
    base = cc.BENCH
    queued = cc.build_registry(
        cc.Profile(n_cns=base.n_cns, n_dps=base.n_dps,
                   n_values=base.n_values, u=base.u, l=base.l,
                   dlog_limit=base.dlog_limit, n_queue=3))
    flat = cc.build_registry(base)
    flat_names = {s.name for s in flat}
    queued_names = {s.name for s in queued}
    assert flat_names <= queued_names
    extra = [s for s in queued if s.name not in flat_names]
    assert extra, "n_queue=3 must add cross-survey programs"
    phases = {s.phase for s in extra}
    assert phases <= {"CrossSurveyVerify", "CrossSurveyVerifyShard"}
    assert "CrossSurveyVerify" in phases
    # the worker-dispatched scalar family is covered at the concat width
    ops = {s.op for s in extra}
    assert {"int_to_scalar", "to_mont_p"} <= ops


def test_registry_n_queue_one_is_identity():
    base = cc.BENCH
    one = cc.build_registry(dataclasses.replace(base, n_queue=1))
    assert {s.name for s in one} == {s.name for s in cc.build_registry(base)}


def test_worker_ops_are_registry_owned_and_covered():
    """The verify-worker op set lives in the registry (cc.WORKER_OPS), so
    the compile lane's `only` filter and the pool's warm-coverage story
    stay in lockstep: every worker op must have a device-family spec in
    any profile, and worker_specs must select exactly those."""
    specs = cc.worker_specs(cc.BENCH)
    assert specs, "worker op set must be covered by the registry"
    assert {s.op for s in specs} == set(cc.WORKER_OPS)
    assert all(s.family == "device" for s in specs)
    names = {s.name for s in cc.build_registry(cc.BENCH)}
    assert {s.name for s in specs} <= names


# -- stub plumbing -----------------------------------------------------------

class _FakeVNs:
    def __init__(self):
        self.flushed: list = []

    def flush_cross_survey(self, sids):
        self.flushed.append(list(sids))
        return list(sids)


class _FakeCluster:
    """Just enough surface for AdmissionController + SurveyServer."""

    def __init__(self):
        self.cns = ["cn0", "cn1"]
        self.dp_idents = ["dp0", "dp1"]
        self.vns = _FakeVNs()
        self.dlog = types.SimpleNamespace(limit=4000)
        self._proof_device_lock = threading.Lock()
        self.executed: list = []
        self.exec_kwargs: list = []
        self.finalized: list = []
        self.fail_encode: set = set()
        self.fail_encode_once: set = set()

    def _ranges_per_value(self, q):
        return [(4, 2)]

    def execute_survey(self, sq, seed=0, hold_range=False,
                       tenant="default", responders=None):
        self.executed.append((sq.survey_id, hold_range,
                              threading.current_thread().name))
        self.exec_kwargs.append((sq.survey_id, tenant, responders))
        if sq.survey_id in self.fail_encode:
            if sq.survey_id in self.fail_encode_once:
                self.fail_encode.discard(sq.survey_id)
                self.fail_encode_once.discard(sq.survey_id)
            raise RuntimeError(f"boom {sq.survey_id}")
        return types.SimpleNamespace(
            sq=sq, hold_range=hold_range, tenant=tenant,
            survey=types.SimpleNamespace(proof_threads=[]))

    def finalize_survey(self, pending):
        sid = pending.sq.survey_id
        self.finalized.append((sid, threading.current_thread().name))
        return f"result-{sid}"


def _sq(sid, proofs=1):
    return types.SimpleNamespace(survey_id=sid,
                                 query=types.SimpleNamespace(proofs=proofs))


@pytest.fixture
def no_compile(monkeypatch):
    """Replace the AOT driver with a recorder: scheduler tests exercise
    lane mechanics, not XLA."""
    calls = []

    def fake_precompile(profile, mode="execute", stats=None, log=None,
                        only=None):
        calls.append((profile, mode, only))
        return {}

    monkeypatch.setattr(cc, "precompile", fake_precompile)
    return calls


# -- admission ---------------------------------------------------------------

def test_admission_proofs_off_is_fast_lane_with_no_profile():
    adm = AdmissionController(_FakeCluster(), n_queue=2)
    a = adm.triage(_sq("s0", proofs=0))
    assert (a.lane, a.profile, a.missing) == ("fast", None, ())


def test_admission_cold_shape_goes_to_compile_lane_then_warms():
    adm = AdmissionController(_FakeCluster(), n_queue=2)
    a = adm.triage(_sq("s0"))
    assert a.lane == "compile" and a.missing
    assert a.profile.n_queue == 2  # batching is certified by admission
    adm.note_warmed(a.profile)
    b = adm.triage(_sq("s1"))
    assert b.lane == "fast" and not b.missing


def test_admission_warmth_is_keyed_by_program_name_not_profile():
    # warming the n_queue=2 profile covers the n_queue=1 subset shape
    cl = _FakeCluster()
    wide = AdmissionController(cl, n_queue=2)
    wide.note_warmed(wide.profile_for(_sq("s0")))
    narrow = AdmissionController(cl, n_queue=1)
    narrow._warm = wide._warm  # same process-wide set in the server
    assert narrow.triage(_sq("s1")).lane == "fast"


# -- scheduler mechanics -----------------------------------------------------

def _warm_server(cl, **kw):
    srv = SurveyServer(cl, **kw)
    srv.admission.note_warmed(srv.admission.profile_for(_sq("_warm")))
    return srv


def test_submit_rejects_past_max_depth_with_typed_error():
    srv = _warm_server(_FakeCluster(), max_depth=2, pipeline=False)
    srv.submit(_sq("s0"))
    srv.submit(_sq("s1"))
    with pytest.raises(QueueFull, match="s2"):
        srv.submit(_sq("s2"))
    # drain frees the depth again
    srv.drain()
    srv.submit(_sq("s2"))


def test_equal_shapes_group_and_flush_once(no_compile):
    cl = _FakeCluster()
    srv = _warm_server(cl, max_batch=3, pipeline=False)
    for i in range(3):
        assert srv.submit(_sq(f"s{i}")).lane == "fast"
    results = srv.drain()
    # one group of 3: every encode held its range payloads, ONE joint
    # flush covered all three surveys
    assert [(sid, h) for sid, h, _ in cl.executed] == [
        ("s0", True), ("s1", True), ("s2", True)]
    assert cl.vns.flushed == [["s0", "s1", "s2"]]
    assert results == {f"s{i}": f"result-s{i}" for i in range(3)}


def test_proofs_off_surveys_never_group():
    cl = _FakeCluster()
    srv = _warm_server(cl, max_batch=3, pipeline=False)
    for i in range(2):
        srv.submit(_sq(f"s{i}", proofs=0))
    srv.drain()
    assert [(sid, h) for sid, h, _ in cl.executed] == [
        ("s0", False), ("s1", False)]
    assert cl.vns.flushed == []


def test_max_batch_caps_the_group(no_compile):
    cl = _FakeCluster()
    srv = _warm_server(cl, max_batch=2, pipeline=False)
    for i in range(3):
        srv.submit(_sq(f"s{i}"))
    srv.drain()
    assert cl.vns.flushed == [["s0", "s1"]]  # s2 ran alone, no hold
    assert cl.executed[2][:2] == ("s2", False)


def test_encode_failure_degrades_one_survey_not_its_batch(no_compile):
    cl = _FakeCluster()
    cl.fail_encode.add("s1")
    srv = _warm_server(cl, max_batch=3, pipeline=False)
    for i in range(3):
        srv.submit(_sq(f"s{i}"))
    results = srv.drain()
    assert isinstance(results["s1"], RuntimeError)
    assert results["s0"] == "result-s0" and results["s2"] == "result-s2"
    # the joint flush proceeded over the survivors only
    assert cl.vns.flushed == [["s0", "s2"]]


def test_compile_lane_promotes_then_executes(no_compile):
    cl = _FakeCluster()
    srv = SurveyServer(cl, max_batch=2, pipeline=False,
                       compile_mode="lower")
    a = srv.submit(_sq("s0"))
    assert a.lane == "compile" and a.missing
    results = srv.drain()
    assert results == {"s0": "result-s0"}
    # the cooperative pass drove the driver (lower + the worker-op
    # execute filter), and the re-admission verdict is now fast
    modes = [m for _, m, _ in no_compile]
    assert modes == ["lower", "execute"]
    assert no_compile[1][2] is not None  # the `only` filter
    assert srv.admission_of("s0").lane == "fast"
    assert srv.timers.spans("Compile.s0")


def test_prewarm_compiles_without_enqueueing(no_compile):
    cl = _FakeCluster()
    srv = SurveyServer(cl, pipeline=False)
    a = srv.prewarm(_sq("s0"))
    assert a.lane == "fast"
    assert no_compile and cl.executed == []
    # a same-shape submit now fast-lanes immediately
    assert srv.submit(_sq("s1")).lane == "fast"


def test_pipeline_mode_verifies_on_the_worker_thread(no_compile):
    cl = _FakeCluster()
    srv = _warm_server(cl, max_batch=2, pipeline=True)
    for i in range(2):
        srv.submit(_sq(f"s{i}"))
    results = srv.drain()
    assert results == {"s0": "result-s0", "s1": "result-s1"}
    # encode on the drain (main) thread, verify on the named worker
    assert {t for _, _, t in cl.executed} == {"MainThread"}
    assert {t for _, t in cl.finalized} == {"server-verify"}


# -- saturation serving: quotas, DRR, shedding, the worker pool, resume ------

def test_quota_exceeded_is_typed_and_per_tenant(no_compile):
    srv = _warm_server(_FakeCluster(), max_depth=16, tenant_quota=2,
                       pipeline=False)
    srv.submit(_sq("a0"), tenant="a")
    srv.submit(_sq("a1"), tenant="a")
    with pytest.raises(QuotaExceeded, match="a2") as ei:
        srv.submit(_sq("a2"), tenant="a")
    assert ei.value.tenant == "a" and ei.value.quota == 2
    assert not isinstance(ei.value, QueueFull)  # distinct typed rejections
    # another tenant is unaffected by a's quota
    srv.submit(_sq("b0"), tenant="b")
    # draining frees a's quota again
    srv.drain()
    srv.submit(_sq("a2"), tenant="a")


def test_queue_full_beats_quota_and_shed_at_max_depth(no_compile):
    # max_depth is the hard bound: at depth 2 the error is QueueFull even
    # though tenant "a" is also past any would-be shed threshold
    srv = _warm_server(_FakeCluster(), max_depth=2, tenant_quota=8,
                       pipeline=False)
    srv.submit(_sq("s0"), tenant="a")
    srv.submit(_sq("s1"), tenant="a")
    with pytest.raises(QueueFull):
        srv.submit(_sq("s2"), tenant="a")


def test_drr_ordering_is_deterministic_across_servers(no_compile):
    """Two identically-configured servers fed the same interleaved
    multi-tenant stream must execute in the same (DRR-predicted)
    order: a gets its max_batch quantum, then b, then c, then back
    to a's remainder."""
    order = [("a0", "a"), ("a1", "a"), ("b0", "b"), ("a2", "a"),
             ("c0", "c"), ("b1", "b"), ("a3", "a")]
    executed = []
    for _ in range(2):
        cl = _FakeCluster()
        srv = _warm_server(cl, max_batch=2, max_depth=16, tenant_quota=8,
                           pipeline=False)
        for sid, tenant in order:
            srv.submit(_sq(sid), tenant=tenant)
        srv.drain()
        executed.append([sid for sid, _, _ in cl.executed])
    assert executed[0] == executed[1]
    assert executed[0] == ["a0", "a1", "b0", "b1", "c0", "a2", "a3"]


def test_hot_tenant_cannot_starve_the_rest(no_compile):
    cl = _FakeCluster()
    srv = _warm_server(cl, max_batch=2, max_depth=32, tenant_quota=16,
                       pipeline=False)
    for i in range(8):
        srv.submit(_sq(f"h{i}"), tenant="hot")
    srv.submit(_sq("v0"), tenant="victim")
    srv.drain()
    sids = [sid for sid, _, _ in cl.executed]
    # the victim ran right after hot's first quantum, not after its 8
    assert sids.index("v0") == 2, sids


def test_shed_rejects_with_retry_after_hint_and_drops_nothing(no_compile):
    from drynx_tpu.resilience import policy as rp

    # max_depth=8, shed fraction 0.75 -> shed past depth 6
    srv = _warm_server(_FakeCluster(), max_depth=8, tenant_quota=8,
                       shed_fraction=0.75, pipeline=False)
    for i in range(6):
        srv.submit(_sq(f"s{i}"))
    with pytest.raises(Overloaded, match="s6") as ei:
        srv.submit(_sq("s6"))
    # cold server (no completion rate yet): the hint is the clamp max
    assert ei.value.retry_after_s == rp.SHED_RETRY_MAX_S
    results = srv.drain()
    # shed never drops admitted work: all six completed
    assert sorted(results) == [f"s{i}" for i in range(6)]
    assert not any(isinstance(r, Exception) for r in results.values())
    # with completions observed, the hint is rate-derived and clamped
    for i in range(6):
        srv.submit(_sq(f"t{i}"))
    with pytest.raises(Overloaded) as ei2:
        srv.submit(_sq("t6"))
    assert rp.SHED_RETRY_MIN_S <= ei2.value.retry_after_s \
        <= rp.SHED_RETRY_MAX_S


def test_shed_fraction_one_disables_shedding(no_compile):
    srv = _warm_server(_FakeCluster(), max_depth=4, tenant_quota=8,
                       shed_fraction=1.0, pipeline=False)
    for i in range(4):
        srv.submit(_sq(f"s{i}"))  # no Overloaded below max_depth
    with pytest.raises(QueueFull):
        srv.submit(_sq("s4"))


def test_worker_pool_spawns_n_named_workers(no_compile):
    cl = _FakeCluster()
    srv = _warm_server(cl, max_batch=2, max_depth=16, tenant_quota=16,
                       pipeline=True, workers=3)
    for i in range(6):
        srv.submit(_sq(f"s{i}"))
    results = srv.drain()
    assert sorted(results) == [f"s{i}" for i in range(6)]
    assert [t.name for t in srv._workers] == [
        "server-verify", "server-verify-1", "server-verify-2"]
    # every finalize ran on a pool thread, never the drain thread
    names = {t for _, t in cl.finalized}
    assert names <= {"server-verify", "server-verify-1", "server-verify-2"}


def test_worker_pool_results_match_single_worker(no_compile):
    outs = []
    for w in (1, 3):
        cl = _FakeCluster()
        srv = _warm_server(cl, max_batch=2, pipeline=True, workers=w,
                           tenant_quota=16)
        for i in range(6):
            srv.submit(_sq(f"s{i}"))
        outs.append((srv.drain(), sorted(map(sorted, cl.vns.flushed))))
    assert outs[0] == outs[1]


def test_resume_requeues_exactly_once_with_probed_responders(no_compile):
    cl = _FakeCluster()
    cl.fail_encode.add("s1")
    cl.fail_encode_once.add("s1")  # transient: second attempt succeeds
    cl.probe_liveness = lambda: {"dp0": True, "dp1": False}
    srv = _warm_server(cl, max_batch=3, pipeline=False)
    for i in range(3):
        srv.submit(_sq(f"s{i}"))
    results = srv.drain()
    # the retried survey completed like a clean run
    assert results == {f"s{i}": f"result-s{i}" for i in range(3)}
    # first attempt unrestricted; the retry carried the probed live set
    attempts = [(sid, resp) for sid, _, resp in cl.exec_kwargs
                if sid == "s1"]
    assert attempts == [("s1", None), ("s1", ("dp0",))]
    # batch partners flushed without waiting on the retry; the retried
    # survey re-entered alone
    assert cl.vns.flushed == [["s0", "s2"]]


def test_resume_gives_up_after_max_retries(no_compile):
    from drynx_tpu.resilience import policy as rp

    cl = _FakeCluster()
    cl.fail_encode.add("s0")  # persistent failure: every attempt raises
    srv = _warm_server(cl, pipeline=False)
    srv.submit(_sq("s0"))
    results = srv.drain()
    assert isinstance(results["s0"], RuntimeError)
    attempts = [sid for sid, _, _ in cl.exec_kwargs if sid == "s0"]
    assert len(attempts) == 1 + rp.RESUME_MAX_RETRIES


def test_resume_budget_widens_for_checkpointed_surveys(no_compile):
    """PR 17: a cluster holding a phase checkpoint for the survey gets
    CHECKPOINT_MAX_RESUMES re-entries (each resumes mid-survey, not from
    scratch); a checkpoint-less survey keeps the legacy single retry."""
    from drynx_tpu.resilience import policy as rp
    from drynx_tpu.service.store import SurveyCheckpoint

    cl = _FakeCluster()
    cl.fail_encode.add("s0")          # persistent failure
    ck = SurveyCheckpoint(survey_id="s0")
    cl.checkpoint_for = lambda sid: ck if sid == "s0" else None
    srv = _warm_server(cl, pipeline=False)
    srv.submit(_sq("s0"))
    results = srv.drain()
    assert isinstance(results["s0"], RuntimeError)
    attempts = [sid for sid, _, _ in cl.exec_kwargs if sid == "s0"]
    assert len(attempts) == 1 + rp.CHECKPOINT_MAX_RESUMES
    assert rp.CHECKPOINT_MAX_RESUMES > rp.RESUME_MAX_RETRIES


def test_resume_e2e_transient_refusal_equals_clean_run():
    """Real LocalCluster (proofs off): a one-shot connect refusal on dp1
    fails the first dispatch's quorum, the resume slice re-probes (the
    refusal is spent), re-enters the queue once, and the retried result
    equals an undisturbed run's."""
    from drynx_tpu.resilience import faults
    from drynx_tpu.service.service import LocalCluster

    def boot():
        cl = LocalCluster(n_cns=1, n_dps=2, n_vns=0, seed=23,
                          dlog_limit=1000)
        rng = np.random.default_rng(9)
        for name, dp in cl.dps.items():
            dp.data = rng.integers(0, 5, size=(3,)).astype(np.int64)
        return cl

    def q(cl, sid):
        return cl.generate_survey_query("sum", query_min=0, query_max=9,
                                        proofs=0, survey_id=sid)

    clean = boot()
    srv0 = SurveyServer(clean, pipeline=False)
    srv0.submit(q(clean, "r0"))
    baseline = srv0.drain()["r0"].result

    plan = faults.FaultPlan(seed=0)
    plan.add(faults.FaultSpec(where="connect", kind="refuse",
                              target="dp1", count=1))
    faults.set_fault_plan(plan)
    try:
        cl = boot()
        srv = SurveyServer(cl, pipeline=False)
        srv.submit(q(cl, "r1"))
        res = srv.drain()["r1"]
    finally:
        faults.set_fault_plan(None)
    assert not isinstance(res, Exception), res
    assert res.result == baseline
    # the retry saw both DPs again: full membership, nothing absent
    assert res.responders == ["dp0", "dp1"] and res.absent == []


@pytest.mark.soak
def test_soak_pause_revive_episode_under_load(monkeypatch):
    """Mini pause/revive soak (the check.sh soak tier; the full harness
    is scripts/bench_soak.py): a healing partition window cuts dp1 from
    the client while a closed-loop LoadGen drives real surveys. The
    checkpointed resume lane paces its re-entries across the heal
    boundary: zero admitted surveys lost, affected surveys resumed from
    their phase checkpoint (probe counter > 1), results equal to an
    undisturbed run."""
    from drynx_tpu.resilience import faults
    from drynx_tpu.server.loadgen import LoadGen, ShapeMix
    from drynx_tpu.service.service import LocalCluster

    # resume passes must re-probe, not reuse a pre-heal verdict
    monkeypatch.setenv("DRYNX_PROBE_TTL", "0.1")

    def boot():
        cl = LocalCluster(n_cns=1, n_dps=2, n_vns=0, seed=23,
                          dlog_limit=1000)
        rng = np.random.default_rng(9)
        for _name, dp in cl.dps.items():
            dp.data = rng.integers(0, 5, size=(3,)).astype(np.int64)
        return cl

    def run(plan):
        faults.set_fault_plan(None)
        cl = boot()
        srv = SurveyServer(cl, max_batch=1, pipeline=False)
        lg = LoadGen(srv, shapes=[ShapeMix("s", proofs=0)], seed=7,
                     query_fn=lambda sid, shape: cl.generate_survey_query(
                         "sum", query_min=0, query_max=9, proofs=0,
                         survey_id=sid))
        if plan is not None:
            faults.set_fault_plan(plan)
            plan.reset_epoch()
        try:
            rep = lg.run_closed(concurrency=1, n_total=3)
        finally:
            faults.set_fault_plan(None)
        res = srv.results()
        return rep, {s: int(r.result) for s, r in res.items()}, res

    _rep, clean_sums, _ = run(None)

    plan = faults.FaultPlan(seed=7)
    plan.add(faults.FaultSpec(where="node", kind="partition", target="*",
                              peer="dp1", after_s=0.0, heal_after_s=0.4))
    rep, sums, res = run(plan)
    assert rep["lost"] == 0 and rep["errors"] == 0
    assert rep["completed"] == 3
    assert sums == clean_sums
    affected = [s for s, r in res.items() if r.resumes > 0]
    assert affected, "the heal window opened at t=0: someone must resume"
    for s in affected:
        assert res[s].phases.get("probe", 0) >= 2  # resumed, not restarted


# -- VN cross-flush: tampered neighbor isolation -----------------------------

def test_cross_flush_isolates_a_tampered_neighbor(tmp_path):
    """Two held surveys flushed in ONE cross-survey dispatch: the survey
    with a tampered payload gets its BM_FALSE, its batch neighbor stays
    fully green — per-survey verdicts split back out of the joint check."""
    from drynx_tpu.crypto import elgamal as eg
    from drynx_tpu.proofs import requests as rq
    from drynx_tpu.service.proof_collection import VerifyingNode

    rng = np.random.default_rng(3)
    sec0, pub0 = eg.keygen(rng)
    sec1, pub1 = eg.keygen(rng)

    def cross(payloads):
        return {sid: [d == b"good" for d in ds]
                for sid, ds in payloads.items()}

    vn = VerifyingNode("vn0", str(tmp_path / "vn0.db"),
                       {"dp0": pub0, "dp1": pub1},
                       verify_fns={"range_cross": cross,
                                   "range_joint":
                                   lambda ds, sid: [d == b"good"
                                                    for d in ds]})
    for sid in ("sv_ok", "sv_bad"):
        vn.register_survey(sid, expected_proofs=2,
                           thresholds={"range": 1.0}, expected_range=2,
                           hold_range=True)
        assert not vn.range_ready(sid)
    deliver = [("sv_ok", "dp0", sec0, b"good"), ("sv_ok", "dp1", sec1,
                                                 b"good"),
               ("sv_bad", "dp0", sec0, b"good"), ("sv_bad", "dp1", sec1,
                                                  b"evil")]
    for sid, dp, sec, data in deliver:
        req = rq.new_proof_request("range", sid, dp, "v0", 0, data, sec)
        # held: buffered, not verified yet
        assert vn.receive_proof(req) == rq.BM_RECVD
    assert vn.range_ready("sv_ok") and vn.range_ready("sv_bad")

    assert sorted(vn.flush_ranges_cross(["sv_ok", "sv_bad"])) == [
        "sv_bad", "sv_ok"]
    assert set(vn.bitmap_for("sv_ok").values()) == {rq.BM_TRUE}
    bad = vn.bitmap_for("sv_bad")
    assert bad["sv_bad/range/dp0/v0"] == rq.BM_TRUE
    assert bad["sv_bad/range/dp1/v0"] == rq.BM_FALSE
    # idempotent: a second flush is a no-op
    assert vn.flush_ranges_cross(["sv_ok", "sv_bad"]) == []


# -- transcripts -------------------------------------------------------------

def _fake_vns():
    vn0 = types.SimpleNamespace(
        name="vn0",
        bitmap_for=lambda sid: {"range-dp1": 101, "range-dp0": 100},
        stored_proofs=lambda sid: {"range-dp0": b"payload0",
                                   "range-dp1": b"payload1"})
    vn1 = types.SimpleNamespace(
        name="vn1",
        bitmap_for=lambda sid: {"range-dp0": 100},
        stored_proofs=lambda sid: {"range-dp0": b"payload0"})
    return types.SimpleNamespace(vns=[vn0, vn1])


def test_transcript_is_deterministic_and_key_sorted():
    vns = _fake_vns()
    t = survey_transcript(vns, "s0")
    lines = t.decode().splitlines()
    assert len(lines) == 3 and t.endswith(b"\n")
    # sorted per VN regardless of bitmap insertion order
    assert [ln.split()[1] for ln in lines] == [
        "range-dp0", "range-dp1", "range-dp0"]
    assert lines[0].split()[0] == "vn0" and lines[2].split()[0] == "vn1"
    assert survey_transcript(_fake_vns(), "s0") == t
    assert transcript_digest(vns, "s0") == transcript_digest(_fake_vns(),
                                                             "s0")


# -- the overlap metric ------------------------------------------------------

def test_pipeline_overlap_intersects_cross_survey_spans_only():
    tm = PhaseTimers()
    tm.span("Pipeline.encode.s0", 0.0, 2.0)
    tm.span("Pipeline.verify.s0", 2.0, 5.0)   # same sid: excluded
    tm.span("Pipeline.encode.s1", 4.0, 7.0)   # overlaps s0's verify by 1s
    tm.span("Pipeline.verify.s1", 7.0, 8.0)
    assert pipeline_overlap(tm) == pytest.approx(1.0)
    assert pipeline_overlap(PhaseTimers()) == 0.0


# -- CLI serve mode ----------------------------------------------------------

def test_cli_survey_run_serve_routes_through_the_server(monkeypatch,
                                                        capsys):
    """`survey run --local --serve N` submits N copies through
    SurveyServer and reports per-survey lane + result (proofs off: one
    cheap in-process cluster, no VNs, no compiles)."""
    import io
    import json

    from drynx_tpu.cmd import client as cli
    from drynx_tpu.cmd import toml_io

    cfg = {"nodes": [{"name": "cn0", "role": "cn",
                      "host": "127.0.0.1", "port": 0},
                     {"name": "dp0", "role": "dp",
                      "host": "127.0.0.1", "port": 0}],
           "survey": {"operation": "sum", "query_min": 0, "query_max": 9,
                      "dlog_limit": 1000}}
    monkeypatch.setattr("sys.stdin", io.StringIO(toml_io.dumps(cfg)))
    rc = cli.main(["survey", "run", "--local", "--serve", "2"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["operation"] == "sum"
    assert set(out["surveys"]) == {"cli0", "cli1"}
    for entry in out["surveys"].values():
        # proofs-off => no profile => always fast lane; sum of the DP's
        # 32 values drawn from [query_min, query_max)
        assert entry["lane"] == "fast"
        assert 0 <= entry["result"] <= 9 * 32


# -- proofs-on end-to-end (slow tier) ----------------------------------------

def _proofs_cluster(seed, data_seed):
    from drynx_tpu.service.service import LocalCluster

    cl = LocalCluster(n_cns=2, n_dps=2, n_vns=2, seed=seed,
                      dlog_limit=4000)
    rng = np.random.default_rng(data_seed)
    per_dp = {}
    for name, dp in cl.dps.items():
        # each DP's local sum must fit the tightest range spec (u=4, l=2
        # => value < 16): two values in [0, 4)
        d = rng.integers(0, 4, size=(2,)).astype(np.int64)
        dp.data = d
        per_dp[name] = d
    return cl, per_dp


def _queries(cl):
    mk = cl.generate_survey_query
    return [mk("sum", query_min=0, query_max=15, proofs=1,
               ranges=[(4, 2)], survey_id="s0"),
            mk("sum", query_min=0, query_max=15, proofs=1,
               ranges=[(4, 2)], survey_id="s1"),
            mk("sum", query_min=0, query_max=15, proofs=1,
               ranges=[(4, 3)], survey_id="s2")]


@pytest.mark.slow
def test_server_end_to_end_batched_equals_serial():
    from drynx_tpu.crypto import batching as B
    from drynx_tpu.proofs import requests as rq

    events = []
    rec = threading.Lock()

    def hook(name):
        with rec:
            events.append((name, threading.current_thread().name))

    cl, per_dp = _proofs_cluster(seed=13, data_seed=5)
    expected = int(np.sum(np.concatenate(list(per_dp.values()))))
    sqs = _queries(cl)
    srv = SurveyServer(cl, max_batch=3, pipeline=True)

    old = B.TRACE_HOOK
    B.TRACE_HOOK = hook
    try:
        srv.prewarm(sqs[0])
        lanes = [srv.submit(sq).lane for sq in sqs]
        results = srv.drain()
    finally:
        B.TRACE_HOOK = old

    # admission: the prewarmed (4,2) shape fast-lanes (twice — one
    # registry drive covers both), the (4,3) shape took the compile lane
    assert lanes == ["fast", "fast", "compile"]
    assert srv.admission_of("s2").lane == "fast"

    for sid in ("s0", "s1", "s2"):
        res = results[sid]
        assert res.result == expected, (sid, res.result)
        assert set(res.block.data.bitmap.values()) == {rq.BM_TRUE}

    # the pipeline actually overlapped encode with a neighbor's verify
    assert pipeline_overlap(srv.timers) > 0.0

    # proof work never first-traced off the drain/main thread
    off_main = sorted({(op, t) for op, t in events if t != "MainThread"})
    assert not off_main, off_main

    batched = {sid: survey_transcript(cl.vns, sid)
               for sid in ("s0", "s1", "s2")}
    assert all(batched.values())

    # the reference configuration: fresh cluster, same seeds, strictly
    # serial verification — transcripts must be byte-identical
    cl2, _ = _proofs_cluster(seed=13, data_seed=5)
    srv2 = SurveyServer(cl2, max_batch=1, pipeline=False)
    for sq in _queries(cl2):
        srv2.submit(sq)
    results2 = srv2.drain()
    for sid in ("s0", "s1", "s2"):
        assert results2[sid].result == expected
        assert survey_transcript(cl2.vns, sid) == batched[sid], sid

    # and the multi-worker pool: same seeds through a 2-worker verify
    # pool — the cross-survey flush is grouping-invariant, so the
    # transcripts stay byte-identical to both references
    cl3, _ = _proofs_cluster(seed=13, data_seed=5)
    srv3 = SurveyServer(cl3, max_batch=3, pipeline=True, workers=2)
    srv3.prewarm(_queries(cl3)[0])
    for sq in _queries(cl3):
        srv3.submit(sq)
    results3 = srv3.drain()
    for sid in ("s0", "s1", "s2"):
        assert results3[sid].result == expected
        assert survey_transcript(cl3.vns, sid) == batched[sid], sid


@pytest.mark.slow
@pytest.mark.chaos
def test_server_soak_with_killed_dp_degrades_without_poisoning():
    from drynx_tpu.proofs import requests as rq
    from drynx_tpu.resilience import faults

    plan = faults.FaultPlan(seed=0)
    plan.add(faults.FaultSpec(where="node", kind="kill", target="dp1"))
    faults.set_fault_plan(plan)
    try:
        cl, per_dp = _proofs_cluster(seed=17, data_seed=7)
        srv = SurveyServer(cl, max_batch=2, pipeline=True)
        mk = cl.generate_survey_query
        sqs = [mk("sum", query_min=0, query_max=15, proofs=1,
                  ranges=[(4, 2)], survey_id=f"c{i}", min_dp_quorum=1)
               for i in range(3)]
        srv.prewarm(sqs[0])
        for sq in sqs:
            srv.submit(sq)
        results = srv.drain()
    finally:
        faults.set_fault_plan(None)

    # every survey degraded the same way — dp1 absent, dp0's data only —
    # and every verdict stayed green: the fault never poisoned neighbors
    expected = int(per_dp["dp0"].sum())
    assert set(results) == {"c0", "c1", "c2"}
    for sid, res in results.items():
        assert not isinstance(res, Exception), (sid, res)
        assert res.result == expected
        assert res.absent == ["dp1"] and res.responders == ["dp0"]
        assert set(res.block.data.bitmap.values()) == {rq.BM_TRUE}
