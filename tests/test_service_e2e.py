"""Full-system in-process survey tests — the reference's TestServiceDrynx
pattern (services/service_test.go:70-349): run the complete query pipeline
over an operation list and assert the decrypted result equals the clear-text
computation; with proofs on, additionally require every bitmap code to be
BM_TRUE and the audit block to exist."""
import numpy as np
import pytest

from drynx_tpu.encoding import stats as st
from drynx_tpu.service.query import DiffPParams
from drynx_tpu.service.service import LocalCluster

pytestmark = pytest.mark.slow  # heavy compiles; fast tier = -m 'not slow'


@pytest.fixture(scope="module")
def cluster():
    # dlog table must cover the largest decrypted value (Σx² for variance)
    return LocalCluster(n_cns=3, n_dps=4, n_vns=0, seed=3, dlog_limit=25000)


def _install_data(cluster, op, rng, rows=24):
    """Give every DP op-appropriate local data; return per-DP arrays."""
    per_dp = []
    for name, dp in cluster.dps.items():
        if op in ("cosim",):
            d = rng.integers(0, 10, size=(rows, 2)).astype(np.int64)
        elif op == "lin_reg":
            X = rng.integers(0, 5, size=(rows, 2)).astype(np.int64)
            y = 2 * X[:, 0] + 3 * X[:, 1] + 1
            d = np.concatenate([X, y[:, None]], axis=1)
        elif op == "r2":
            d = rng.integers(0, 8, size=(rows,)).astype(np.int64)
        elif op in ("bool_OR", "bool_AND"):
            d = rng.integers(0, 2, size=(rows,)).astype(np.int64)
        else:
            d = rng.integers(0, 15, size=(rows,)).astype(np.int64)
        dp.data = d
        per_dp.append(d)
    return per_dp


OPS_NO_PROOF = ["sum", "mean", "variance", "frequency_count", "min", "max",
                "union", "inter", "bool_OR", "bool_AND"]


@pytest.mark.parametrize("op", OPS_NO_PROOF)
def test_survey_matches_cleartext(cluster, op):
    rng = np.random.default_rng(hash(op) % 2**31)
    per_dp = _install_data(cluster, op, rng)
    qmin, qmax = 0, 15
    sq = cluster.generate_survey_query(op, query_min=qmin, query_max=qmax)
    res = cluster.run_survey(sq)

    allv = np.concatenate(per_dp)
    if op == "sum":
        assert res.result == int(allv.sum())
    elif op == "mean":
        assert res.result == pytest.approx(float(allv.mean()))
    elif op == "variance":
        assert res.result == pytest.approx(float(allv.var()), rel=1e-9)
    elif op == "frequency_count":
        want = {v: int((allv == v).sum()) for v in range(qmin, qmax + 1)}
        assert res.result == want
    elif op == "min":
        assert res.result == int(allv.min())
    elif op == "max":
        assert res.result == int(allv.max())
    elif op == "union":
        assert sorted(res.result) == sorted(set(allv.tolist()))
    elif op == "inter":
        inter = set(per_dp[0].tolist())
        for d in per_dp[1:]:
            inter &= set(d.tolist())
        assert sorted(res.result) == sorted(inter)
    elif op == "bool_OR":
        assert res.result == bool(np.any(allv != 0))
    elif op == "bool_AND":
        assert res.result == bool(np.all(
            [np.all(d != 0) for d in per_dp]))


def test_survey_cosim_and_linreg_and_r2(cluster):
    rng = np.random.default_rng(77)
    per_dp = _install_data(cluster, "cosim", rng)
    sq = cluster.generate_survey_query("cosim")
    res = cluster.run_survey(sq)
    allv = np.concatenate(per_dp)
    a, b = allv[:, 0].astype(float), allv[:, 1].astype(float)
    want = float((a * b).sum() / (np.sqrt((a * a).sum()) * np.sqrt((b * b).sum())))
    assert res.result == pytest.approx(want, rel=1e-9)

    per_dp = _install_data(cluster, "lin_reg", rng)
    sq = cluster.generate_survey_query("lin_reg", dims=2)
    res = cluster.run_survey(sq)
    # y = 1 + 2 x0 + 3 x1 exactly -> coefficients recovered exactly
    assert np.allclose(res.result, [1.0, 2.0, 3.0], atol=1e-8)


def test_survey_obfuscation_preserves_zeroness(cluster):
    rng = np.random.default_rng(5)
    _install_data(cluster, "union", rng)
    sq = cluster.generate_survey_query("union", query_min=0, query_max=15,
                                       obfuscation=True)
    res_plain = cluster.run_survey(
        cluster.generate_survey_query("union", query_min=0, query_max=15))
    res_obf = cluster.run_survey(sq)
    assert sorted(res_obf.result) == sorted(res_plain.result)


def test_survey_diffp_adds_noise(cluster):
    rng = np.random.default_rng(6)
    per_dp = _install_data(cluster, "sum", rng)
    diffp = DiffPParams(noise_list_size=16, lap_mean=0.0, lap_scale=2.0,
                        quanta=1.0, scale=1.0, limit=8.0)
    sq = cluster.generate_survey_query("sum", query_min=0, query_max=15,
                                       diffp=diffp)
    res = cluster.run_survey(sq)
    clear = int(np.concatenate(per_dp).sum())
    # noise list values are bounded by limit*scale
    assert abs(res.result - clear) <= 8


def test_survey_cutting_factor_replicates_ciphertexts(cluster):
    """CuttingFactor scale testing (round-2 VERDICT missing #5): the DP
    output vector (and every downstream ciphertext) is replicated cf times
    (reference lib/structs.go:637-639) yet the decoded result is unchanged."""
    rng = np.random.default_rng(17)
    per_dp = _install_data(cluster, "sum", rng)
    sq = cluster.generate_survey_query("sum", query_min=0, query_max=15,
                                       cutting_factor=3)
    assert sq.query.operation.nbr_output == 3  # 1 output replicated x3
    res = cluster.run_survey(sq)
    assert res.result == int(np.concatenate(per_dp).sum())
    # the wire carried all 3 replicas and they decrypted identically
    assert res.decrypted.values.shape[0] == 1  # sliced back for decoding


def test_shuffle_precomp_persists_across_restart(tmp_path):
    """The precomputation pool survives a process restart via its disk cache
    (reference pre_compute_multiplications.gob, service.go:34,316-317)."""
    cache = str(tmp_path / "precomp")
    cl1 = LocalCluster(n_cns=2, n_dps=2, n_vns=0, seed=19, dlog_limit=2000)
    cl1.prewarm_dro(noise_size=8, n_surveys=1, cache_dir=cache)
    import glob

    files = glob.glob(cache + "/precomp_*.npz")
    assert len(files) == 2  # one per CN

    # "restart": a fresh cluster object with the same roster seed reloads
    cl2 = LocalCluster(n_cns=2, n_dps=2, n_vns=0, seed=19, dlog_limit=2000)
    assert cl2.load_shuffle_precomp(cache) == 2
    for dp in cl2.dps.values():
        dp.data = np.arange(4, dtype=np.int64)
    diffp = DiffPParams(noise_list_size=8, lap_mean=0.0, lap_scale=2.0,
                        quanta=1.0, scale=1.0, limit=4.0)
    sq = cl2.generate_survey_query("sum", query_min=0, query_max=5,
                                   diffp=diffp)
    res = cl2.run_survey(sq)
    assert abs(res.result - 2 * 6) <= 4  # sum=12 plus bounded noise
    # consume-once: the used entries' files are gone
    assert glob.glob(cache + "/precomp_*.npz") == []
