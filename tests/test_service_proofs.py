"""Proofs-on full-system survey tests, split from test_service_e2e so the
file runs in its own process: XLA's CPU compiler degrades after the ~14
compiles the no-proof op sweep accumulates, and the NEXT compile (these
tests') segfaults — in isolation both pass in ~4 min (see pytest.ini /
scripts/run_suite.py for the isolation strategy)."""
import numpy as np
import pytest

from drynx_tpu.proofs import requests as rq
from drynx_tpu.service.service import LocalCluster

pytestmark = pytest.mark.slow  # heavy compiles; fast tier = -m 'not slow'


@pytest.fixture(scope="module")
def cluster_proofs():
    return LocalCluster(n_cns=2, n_dps=2, n_vns=2, seed=11, dlog_limit=4000)


def test_survey_with_proofs_commits_clean_bitmap(cluster_proofs):
    cl = cluster_proofs
    rng = np.random.default_rng(8)
    per_dp = []
    for dp in cl.dps.values():
        d = rng.integers(0, 10, size=(16,)).astype(np.int64)
        dp.data = d
        per_dp.append(d)
    sq = cl.generate_survey_query("sum", query_min=0, query_max=15, proofs=1,
                                  ranges=[(4, 4)])  # sums < 256
    res = cl.run_survey(sq)
    assert res.result == int(np.concatenate(per_dp).sum())
    assert res.block is not None
    codes = set(res.block.data.bitmap.values())
    assert codes == {rq.BM_TRUE}, res.block.data.bitmap
    assert cl.vns.root.chain.validate()


def test_survey_with_proofs_mixed_ranges(cluster_proofs):
    """Per-value range specs (round-1 weakness #4 / VERDICT task 7): a mean
    query proves its sum and its count against DIFFERENT (u, l) bounds
    (reference validates per-index ranges, lib/structs.go:446-533)."""
    cl = cluster_proofs
    rng = np.random.default_rng(9)
    per_dp = []
    for dp in cl.dps.values():
        d = rng.integers(0, 10, size=(16,)).astype(np.int64)
        dp.data = d
        per_dp.append(d)
    # per-DP sum < 160 <= 4^4; per-DP count = 16 < 4^3
    sq = cl.generate_survey_query("mean", query_min=0, query_max=15, proofs=1,
                                  ranges=[(4, 4), (4, 3)])
    res = cl.run_survey(sq)
    allv = np.concatenate(per_dp)
    assert res.result == pytest.approx(float(allv.mean()))
    assert res.block is not None
    assert set(res.block.data.bitmap.values()) == {rq.BM_TRUE}
