"""Zero jit tracing on non-main threads during a proofs-on survey.

The r05 segfault class: partial_eval recurses ~1 C frame per traced
equation, the pairing kernels trace >10k equations, and worker threads get
half the main thread's C stack — first-touch tracing from an _async_proof /
dp_lists thread killed the process with no Python traceback. The fix is
structural (LocalCluster._warm_kernels dispatches the whole compilecache
registry on the main thread before any proof thread exists, plus
compilecache.trace_guard); this test pins the INVARIANT: every bucketed
trace event during a cold proofs-on survey happens on MainThread.

batching.TRACE_HOOK fires inside the wrapped fn body, which jax runs ONLY
on a jit-cache miss — the hook observes real retraces, not mere calls.
Own file so scripts/run_suite.py gives it a cold process (warm jit caches
from a sibling test would hide trace events)."""
import threading

import numpy as np
import pytest

from drynx_tpu.crypto import batching as B
from drynx_tpu.proofs import requests as rq
from drynx_tpu.service.service import LocalCluster

pytestmark = pytest.mark.slow  # proofs-on survey: pairing-heavy compiles


def test_proofs_on_survey_traces_only_on_main_thread():
    events: list[tuple[str, str]] = []
    rec_lock = threading.Lock()

    def hook(name: str) -> None:
        with rec_lock:
            events.append((name, threading.current_thread().name))

    old = B.TRACE_HOOK
    B.TRACE_HOOK = hook
    try:
        cl = LocalCluster(n_cns=2, n_dps=2, n_vns=2, seed=13,
                          dlog_limit=4000)
        rng = np.random.default_rng(5)
        per_dp = []
        for dp in cl.dps.values():
            d = rng.integers(0, 10, size=(16,)).astype(np.int64)
            dp.data = d
            per_dp.append(d)
        sq = cl.generate_survey_query("sum", query_min=0, query_max=15,
                                      proofs=1, ranges=[(4, 4)])
        res = cl.run_survey(sq)
    finally:
        B.TRACE_HOOK = old

    # the survey itself must have succeeded (clean bitmap, right answer)
    assert res.result == int(np.concatenate(per_dp).sum())
    assert set(res.block.data.bitmap.values()) == {rq.BM_TRUE}

    off_main = sorted({(op, t) for op, t in events if t != "MainThread"})
    assert not off_main, (
        f"first-touch jit tracing on worker threads (the r05 segfault "
        f"class): {off_main}")
