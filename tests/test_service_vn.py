"""Native proof store, skipchain-equivalent, and VN proof collection."""
import numpy as np
import pytest

from drynx_tpu.crypto import elgamal as eg
from drynx_tpu.proofs import requests as rq
from drynx_tpu.service.proof_collection import VerifyingNode, VNGroup
from drynx_tpu.service.skipchain import DataBlock, SkipChain
from drynx_tpu.service.store import ProofDB, SurveyCheckpoint


def test_proofdb_roundtrip(tmp_path):
    db = ProofDB(str(tmp_path / "p.db"))
    db.put("a/b", b"hello")
    db.put("a/c", b"world")
    db.put("a/b", b"hello2")  # overwrite
    assert db.get("a/b") == b"hello2"
    assert db.get("a/c") == b"world"
    assert db.get("missing") is None
    assert sorted(db.keys()) == [b"a/b", b"a/c"]
    db.close()
    # persistence across reopen
    db2 = ProofDB(str(tmp_path / "p.db"))
    assert db2.get("a/b") == b"hello2"
    db2.close()


def test_proofdb_is_native(tmp_path):
    db = ProofDB(str(tmp_path / "n.db"))
    assert db.native, "native C++ proofdb failed to build/load"
    db.close()


def test_survey_checkpoint_roundtrip_and_reopen(tmp_path):
    """PR 17: the phase checkpoint rides the proof log under the ckpt:
    prefix and survives a root process restart (reopen)."""
    db = ProofDB(str(tmp_path / "ck.db"))
    ck = SurveyCheckpoint(survey_id="sv1")
    ck.enter("probe")
    ck.enter("collect")
    ck.enter("collect")            # a healing re-entry
    ck.responders = ["dp0", "dp2"]
    ck.absent = ["dp1"]
    ck.resumes = 1
    ck.save(db)
    # same record after a byte roundtrip
    back = SurveyCheckpoint.from_bytes(ck.to_bytes())
    assert back == ck
    assert back.phase == "collect"
    assert back.phase_entries == {"probe": 1, "collect": 2}
    db.close()
    # a restarted root reads it back from the reopened log
    db2 = ProofDB(str(tmp_path / "ck.db"))
    again = SurveyCheckpoint.load(db2, "sv1")
    assert again == ck
    assert SurveyCheckpoint.load(db2, "missing") is None
    db2.close()
    # None store: save/load degrade to no-ops (in-memory-only clusters)
    ck.save(None)
    assert SurveyCheckpoint.load(None, "sv1") is None


def test_skipchain_append_and_validate(tmp_path):
    db = ProofDB(str(tmp_path / "c.db"))
    chain = SkipChain(db)
    b0 = chain.append(DataBlock("sv0", 1.0, {"k": 1}))
    b1 = chain.append(DataBlock("sv1", 2.0, {"k": 0}))
    assert b0.index == 0 and b1.prev_hash == b0.hash()
    assert chain.validate()
    assert chain.latest().data.survey_id == "sv1"
    assert chain.block_for_survey("sv0").data.bitmap == {"k": 1}
    db.close()
    # reload keeps the chain
    chain2 = SkipChain(ProofDB(str(tmp_path / "c.db")))
    assert len(chain2) == 2 and chain2.validate()


def test_vn_group_collects_and_commits(tmp_path):
    rng = np.random.default_rng(1)
    dp_secret, dp_pub = eg.keygen(rng)
    pubs = {"dp0": dp_pub}
    vns = [VerifyingNode(f"vn{i}", str(tmp_path / f"vn{i}.db"), pubs,
                         verify_fns={"aggregation": lambda d, _s: d == b"good"},
                         seed=i) for i in range(3)]
    group = VNGroup(vns)
    group.register_survey("sv", expected_proofs=2,
                          thresholds={"aggregation": 1.0, "range": 1.0})

    r1 = rq.new_proof_request("aggregation", "sv", "dp0", "g0", 0, b"good",
                              dp_secret)
    r2 = rq.new_proof_request("aggregation", "sv", "dp0", "g1", 0, b"bad",
                              dp_secret)
    assert group.deliver(r1) == [rq.BM_TRUE] * 3
    assert group.deliver(r2) == [rq.BM_FALSE] * 3

    block = group.end_verification("sv", timeout=5.0)
    assert block.data.survey_id == "sv"
    assert block.data.bitmap["vn0:sv/aggregation/dp0/g0"] == rq.BM_TRUE
    assert block.data.bitmap["vn1:sv/aggregation/dp0/g1"] == rq.BM_FALSE
    assert vns[0].chain.validate()
    # raw proof bytes retrievable (reference SendGetProofs)
    stored = vns[1].stored_proofs("sv")
    assert stored["sv/aggregation/dp0/g0"] == b"good"
