"""Verifiable shuffle proof: honest shuffle verifies, cheats are rejected."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from drynx_tpu.crypto import curve as C
from drynx_tpu.crypto import elgamal as eg
from drynx_tpu.crypto import field as F
from drynx_tpu.crypto import params
from drynx_tpu.proofs import shuffle as sp

pytestmark = pytest.mark.slow  # heavy compiles; fast tier = -m 'not slow'

RNG = np.random.default_rng(5)
K = 5


@pytest.fixture(scope="module")
def setup():
    x, pub = eg.keygen(RNG)
    tbl = eg.pub_table(pub)
    h_pt = jnp.asarray(C.from_ref(pub))
    vals = np.arange(K, dtype=np.int64)
    cts, _ = eg.encrypt_ints(jax.random.PRNGKey(0), tbl, vals)
    return tbl, h_pt, cts


def _do_shuffle(cts, tbl, perm, betas):
    """out[j] = cts[perm[j]] + Enc_beta[j](0)."""
    shuffled = jnp.take(cts, jnp.asarray(perm), axis=0)
    rs = jnp.asarray(np.stack([F.from_int(b) for b in betas]))
    zero = eg.int_to_scalar(jnp.zeros((K,), dtype=jnp.int64))
    zero_ct = eg.encrypt_with_tables(eg.BASE_TABLE.table, tbl.table, zero, rs)
    return eg.ct_add(shuffled, zero_ct)


def test_ilmpp_roundtrip():
    rng = np.random.default_rng(3)
    xs = [int(rng.integers(2, 1 << 60)) for _ in range(4)]
    # ys with same product: permute xs and multiply/divide a pair
    ys = [xs[1], xs[0], xs[3], xs[2]]
    X = sp._base_muls(xs)
    Y = sp._base_muls(ys)
    proof = sp.ilmpp_prove(xs, ys, X, Y, rng)
    assert sp.ilmpp_verify(proof, X, Y)
    # different product must fail
    ys_bad = list(ys)
    ys_bad[0] = (ys_bad[0] + 1) % params.N
    Y_bad = sp._base_muls(ys_bad)
    bad = sp.ilmpp_prove(xs, ys_bad, X, Y_bad, rng)
    assert not sp.ilmpp_verify(bad, X, Y_bad)


def test_shuffle_proof_roundtrip(setup):
    tbl, h_pt, cts = setup
    rng = np.random.default_rng(9)
    perm = rng.permutation(K)
    betas = [int(rng.integers(1, 1 << 62)) for _ in range(K)]
    out = _do_shuffle(cts, tbl, perm, betas)
    proof = sp.prove_shuffle(cts, out, perm, betas, h_pt, rng)
    assert sp.verify_shuffle(proof, cts, out, h_pt)
    assert len(proof.to_bytes()) > 0


def test_shuffle_proof_rejects_value_change(setup):
    tbl, h_pt, cts = setup
    rng = np.random.default_rng(11)
    perm = rng.permutation(K)
    betas = [int(rng.integers(1, 1 << 62)) for _ in range(K)]
    out = _do_shuffle(cts, tbl, perm, betas)
    # cheat: replace one output with an encryption of a different value
    evil, _ = eg.encrypt_ints(jax.random.PRNGKey(5), tbl,
                              np.asarray([99], dtype=np.int64))
    out_bad = out.at[2].set(evil[0])
    proof = sp.prove_shuffle(cts, out_bad, perm, betas, h_pt, rng)
    assert not sp.verify_shuffle(proof, cts, out_bad, h_pt)


def test_shuffle_proof_rejects_duplicate(setup):
    tbl, h_pt, cts = setup
    rng = np.random.default_rng(13)
    perm = np.asarray([0, 0, 2, 3, 4])  # not a permutation: duplicates 0
    betas = [int(rng.integers(1, 1 << 62)) for _ in range(K)]
    out = _do_shuffle(cts, tbl, perm, betas)
    proof = sp.prove_shuffle(cts, out, perm, betas, h_pt, rng)
    assert not sp.verify_shuffle(proof, cts, out, h_pt)
