"""Simulation harness: grid rows run end-to-end and emit the phase CSV."""
import pytest

pytestmark = pytest.mark.slow  # compiles crypto kernels; fast tier = -m "not slow"

from drynx_tpu.simul import SimulationConfig, run_simulation
from drynx_tpu.simul.runner import results_csv


def test_simulation_single_run():
    cfg = SimulationConfig(nbr_servers=2, nbr_dps=3, operation="mean",
                           rows_per_dp=8, dlog_limit=2000, seed=4)
    out = run_simulation(cfg)
    assert isinstance(out["result"], float)
    assert out["timings"]["JustExecution"] > 0
    assert "AggregationPhase" in out["timings"]

    csv = results_csv([out])
    lines = csv.strip().split("\n")
    assert len(lines) == 2 and lines[0].startswith("operation,")
