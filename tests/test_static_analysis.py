"""Tier-1 gate: the static analyzer must be clean over drynx_tpu/.

Runs the AST lint pass (drynx_tpu.analysis, see ANALYSIS.md) against the
committed tree and asserts zero unbaselined findings, a healthy baseline
(no stale entries, every entry justified), and that the CLI actually
fails on a violation — so the gate can't rot into a tautology.

Marked `lint`: `pytest -m lint` runs just this file in seconds. The
analysis package deliberately imports no jax, so this test stays alive
even when the accelerator stack is broken.
"""
import subprocess
import sys

import pytest

from drynx_tpu.analysis import (DEFAULT_BASELINE, REPO_ROOT, RULES,
                                analyze_paths, apply_baseline, load_baseline)

pytestmark = pytest.mark.lint

PACKAGE = REPO_ROOT / "drynx_tpu"


def test_registry_has_the_documented_rules():
    expected = {"jit-global-capture", "unsafe-pickle", "implicit-dtype",
                "host-sync-in-hot-path", "env-read-into-trace",
                "secret-logging", "hardcoded-timeout", "thread-trace"}
    assert expected <= set(RULES), sorted(expected - set(RULES))


def test_tree_is_clean_modulo_baseline():
    findings = analyze_paths([PACKAGE])
    baseline = load_baseline(DEFAULT_BASELINE)
    unbaselined, matched, stale = apply_baseline(findings, baseline)
    assert not unbaselined, "unbaselined findings:\n" + "\n".join(
        f.render() for f in unbaselined)
    assert not stale, ("stale baseline entries (prune LINT_BASELINE.json):"
                       "\n" + "\n".join(f"[{e.rule}] {e.file}: "
                                        f"{e.line_text!r}" for e in stale))
    # the INTERPRET/UNROLL debt is burned down: the baseline is EMPTY and
    # should stay that way — every entry must grandfather real findings
    assert matched == sum(e.count for e in baseline)


def test_every_baseline_entry_is_justified():
    for e in load_baseline(DEFAULT_BASELINE):
        assert e.why.strip(), f"baseline entry without a why: {e.file} " \
                              f"[{e.rule}] {e.line_text!r}"
        assert e.count >= 1


VIOLATION = (
    "import pickle\n"
    "def load(blob):\n"
    "    return pickle.loads(blob)\n"
)


def _cli(args):
    return subprocess.run(
        [sys.executable, "-m", "drynx_tpu.analysis", *args],
        capture_output=True, text=True, cwd=REPO_ROOT)


def test_cli_exits_zero_on_the_tree():
    proc = _cli([str(PACKAGE)])
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_fails_on_a_synthetic_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(VIOLATION)
    proc = _cli([str(bad)])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "unsafe-pickle" in proc.stdout


def test_cli_passes_a_clean_file(tmp_path):
    ok = tmp_path / "ok.py"
    ok.write_text("import numpy as np\n\nX = np.zeros((4,), np.uint32)\n")
    proc = _cli([str(ok)])
    assert proc.returncode == 0, proc.stdout + proc.stderr
