"""Tier-1 gate: the static analyzer must be clean over drynx_tpu/.

Runs the AST lint pass (drynx_tpu.analysis, see ANALYSIS.md) against the
committed tree and asserts zero unbaselined findings, a healthy baseline
(no stale entries, every entry justified), and that the CLI actually
fails on a violation — so the gate can't rot into a tautology.

Marked `lint`: `pytest -m lint` runs just this file in seconds. The
analysis package deliberately imports no jax, so this test stays alive
even when the accelerator stack is broken.
"""
import json
import os
import subprocess
import sys

import pytest

from drynx_tpu.analysis import (DEFAULT_BASELINE, REPO_ROOT, RULES,
                                ProjectInfo, analyze_paths, analyze_project,
                                apply_baseline, load_baseline)

pytestmark = pytest.mark.lint

PACKAGE = REPO_ROOT / "drynx_tpu"
FIXTURE = REPO_ROOT / "tests" / "fixtures" / "lintpkg"
GOLDEN_GRAPH = REPO_ROOT / "tests" / "fixtures" / "lintpkg_graph.json"


def test_registry_has_the_documented_rules():
    expected = {"jit-global-capture", "cross-module-flag-capture",
                "unsafe-pickle", "implicit-dtype", "host-sync-in-hot-path",
                "pallas-operand-dtype", "env-read-into-trace",
                "secret-logging", "hardcoded-timeout", "thread-trace",
                "ciphertext-dtype-launder", "secret-flow-to-sink",
                "unguarded-shared-mutation", "lock-order-inversion",
                "blocking-call-under-lock", "nondet-flow-to-transcript",
                "unordered-iteration-at-sink", "atomic-durable-write",
                "slab-consumption-order", "conn-checkout-discipline",
                "seal-commit-once"}
    assert expected <= set(RULES), sorted(expected - set(RULES))


def test_tree_is_clean_modulo_baseline():
    findings = analyze_paths([PACKAGE])
    baseline = load_baseline(DEFAULT_BASELINE)
    unbaselined, matched, stale = apply_baseline(findings, baseline)
    assert not unbaselined, "unbaselined findings:\n" + "\n".join(
        f.render() for f in unbaselined)
    assert not stale, ("stale baseline entries (prune LINT_BASELINE.json):"
                       "\n" + "\n".join(f"[{e.rule}] {e.file}: "
                                        f"{e.line_text!r}" for e in stale))
    # the INTERPRET/UNROLL debt is burned down: the baseline is EMPTY and
    # should stay that way — every entry must grandfather real findings
    assert matched == sum(e.count for e in baseline)


def test_every_baseline_entry_is_justified():
    for e in load_baseline(DEFAULT_BASELINE):
        assert e.why.strip(), f"baseline entry without a why: {e.file} " \
                              f"[{e.rule}] {e.line_text!r}"
        assert e.count >= 1


VIOLATION = (
    "import pickle\n"
    "def load(blob):\n"
    "    return pickle.loads(blob)\n"
)


def _cli(args):
    return subprocess.run(
        [sys.executable, "-m", "drynx_tpu.analysis", *args],
        capture_output=True, text=True, cwd=REPO_ROOT)


def test_cli_exits_zero_on_the_tree():
    proc = _cli([str(PACKAGE)])
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_fails_on_a_synthetic_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(VIOLATION)
    proc = _cli([str(bad)])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "unsafe-pickle" in proc.stdout


def test_cli_passes_a_clean_file(tmp_path):
    ok = tmp_path / "ok.py"
    ok.write_text("import numpy as np\n\nX = np.zeros((4,), np.uint32)\n")
    proc = _cli([str(ok)])
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- whole-program pass ------------------------------------------------------

def test_project_pass_is_clean_and_fast():
    # the acceptance budget: import graph + callgraph + every project
    # rule (all five engine families) over the whole package, zero
    # findings. Measured in a fresh interpreter — the way the pass
    # actually runs (check.sh lint tiers, the CLI): inside a long
    # pytest session the accumulated heap roughly doubles the
    # in-process wall time, which says nothing about the pass itself.
    # Budget 7s: idle measures ~4.7s after the typestate engine joined
    # (the quadratic ModuleInfo scans were flattened to pay for it);
    # the headroom absorbs a loaded CI box, not engine growth.
    prog = (
        "import json, sys, time\n"
        "from drynx_tpu.analysis.project import analyze_project\n"
        "t0 = time.monotonic()\n"
        "findings = analyze_project([%r])\n"
        "json.dump({'elapsed': time.monotonic() - t0,\n"
        "           'findings': [f.render() for f in findings]}, sys.stdout)\n"
        % str(PACKAGE))
    env = dict(os.environ, DRYNX_SKIP_JAX_INIT="1")
    proc = subprocess.run([sys.executable, "-c", prog], cwd=str(REPO_ROOT),
                          capture_output=True, text=True, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert out["findings"] == [], "\n".join(out["findings"])
    assert out["elapsed"] < 7.0, \
        f"project pass took {out['elapsed']:.1f}s (budget 7s)"


def test_list_rules_marks_project_rules():
    proc = _cli(["--list-rules"])
    assert proc.returncode == 0
    assert "pallas-operand-dtype [project]:" in proc.stdout
    assert "cross-module-flag-capture [project]:" in proc.stdout
    assert "unsafe-pickle:" in proc.stdout  # per-module rules unmarked


def test_fixture_package_yields_exactly_the_nineteen_findings():
    proc = _cli([str(FIXTURE), "--no-baseline"])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    out = proc.stdout
    for rule in ("cross-module-flag-capture", "host-sync-in-hot-path",
                 "pallas-operand-dtype", "ciphertext-dtype-launder",
                 "lock-order-inversion", "blocking-call-under-lock",
                 "atomic-durable-write", "slab-consumption-order",
                 "conn-checkout-discipline", "seal-commit-once"):
        assert out.count(f"[{rule}]") == 1, out
    # announce + annotated_leak (annotation seed) + batch_leak (container
    # mutation) — see the fixture docstring
    assert out.count("[secret-flow-to-sink]") == 3, out
    # UNGUARDED is bumped bare from both thread entries: one per site
    assert out.count("[unguarded-shared-mutation]") == 2, out
    # determinism.py: time->digest + urandom->put, set-iteration +
    # unsorted-listing — two per determinism rule
    assert out.count("[nondet-flow-to-transcript]") == 2, out
    assert out.count("[unordered-iteration-at-sink]") == 2, out
    assert out.count("call chain:") == 19, out


def test_json_format_has_stable_call_chain_field():
    proc = _cli([str(FIXTURE), "--no-baseline", "--format", "json"])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    findings = data["findings"]
    assert len(findings) == 19
    for f in findings:
        assert isinstance(f["call_chain"], list) and f["call_chain"]
        assert all(isinstance(h, str) for h in f["call_chain"])
    sync = next(f for f in findings if f["rule"] == "host-sync-in-hot-path")
    assert sync["call_chain"][0].endswith(":checksum")
    assert sync["call_chain"][-1].endswith(":float()")


def test_fixture_graphs_match_golden_json():
    project, errors = ProjectInfo.from_paths([FIXTURE])
    assert errors == []
    golden = json.loads(GOLDEN_GRAPH.read_text(encoding="utf-8"))
    assert project.to_json() == golden


def test_changed_only_mode_runs():
    # inside the repo git is available: either "no changed python files"
    # (clean tree) or a whole-package scan reported only over the
    # *impacted set* (changed files + transitive importers) — both exit
    # 0/1, never a usage error.
    proc = _cli(["--changed-only"])
    assert proc.returncode in (0, 1), proc.stdout + proc.stderr
