"""Streaming surveys (PR 18, drynx_tpu/service/streaming.py).

Four properties carry the design and each gets direct coverage here:

  * **Epsilon single-spend** — the per-(DP, cohort) budget must admit a
    charge exactly once whatever the interleaving: across racing threads
    AND across a process restart (the fsync'd journal replays as spent).
    Mirrors the DRO slab double-consumption pair in test_pool.py — same
    privacy argument, different ledger.
  * **Decode modes** — quantile / median / top_k are pure host-side
    walks over the frequency_count histogram, with the sparse-grid
    sentinel table (empty window -> None / []) mirroring
    decode_grouped's ambiguity rules.
  * **Expired-pane subtraction exactness** — ct_sub of an expired pane
    followed by canon_points yields BYTES equal to a from-scratch fold
    of the remaining window (abelian cancellation mod p; the streaming
    extension of test_topology.py's fold-associativity contract).
  * **Delta == from-scratch through the full pipeline** (slow tier) —
    at 1/2/4-pane slides a delta advance and a fresh engine re-fed the
    same rows produce identical survey ids, results, decrypted bytes
    and VN proof transcripts; pane proof blobs persisted in a ProofDB
    are reused byte-identically by a restarted engine with zero new
    proof creations.
"""
import threading
from collections import Counter

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from drynx_tpu import pool as pool_mod  # noqa: E402
from drynx_tpu.crypto import elgamal as eg  # noqa: E402
from drynx_tpu.encoding import stats as st  # noqa: E402
from drynx_tpu.service import topology as topo  # noqa: E402
from drynx_tpu.service.store import ProofDB, pane_key  # noqa: E402


# -- epsilon ledger: single-spend across threads and restarts ---------------

def test_epsilon_exact_budget_and_typed_rejection(tmp_path):
    led = pool_mod.EpsilonLedger(str(tmp_path), budget=1.0)
    for _ in range(4):
        led.charge("dp1", "cohortA", 0.25)
    # 4 x 0.25 lands exactly at the budget (float drift absorbed by the
    # ledger's slack) -- admitted; the 5th is the typed rejection
    assert led.spent("dp1", "cohortA") == pytest.approx(1.0)
    assert led.remaining("dp1", "cohortA") == pytest.approx(0.0)
    with pytest.raises(pool_mod.EpsilonExhausted):
        led.charge("dp1", "cohortA", 0.25)
    assert isinstance(pool_mod.EpsilonExhausted("x"), pool_mod.PoolError)
    # budgets are per (dp, cohort): other identities are untouched
    led.charge("dp2", "cohortA", 0.25)
    led.charge("dp1", "cohortB", 0.25)
    assert led.counters["charges"] == 6
    assert led.counters["rejections"] == 1


def test_epsilon_negative_charge_rejected(tmp_path):
    led = pool_mod.EpsilonLedger(str(tmp_path), budget=1.0)
    with pytest.raises(pool_mod.PoolError):
        led.charge("dp1", "c", -0.1)
    assert led.spent("dp1", "c") == 0.0


def test_epsilon_double_spend_across_threads(tmp_path):
    """8 threads race one remaining 0.1 of budget: exactly one wins
    (test_pool.py's slab double-consumption barrier, ported)."""
    led = pool_mod.EpsilonLedger(str(tmp_path), budget=0.1)
    barrier = threading.Barrier(8)
    wins, raises = [], []

    def racer():
        barrier.wait()
        try:
            led.charge("dp1", "cohortA", 0.1)
            wins.append(1)
        except pool_mod.EpsilonExhausted:
            raises.append(1)

    threads = [threading.Thread(target=racer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1 and len(raises) == 7
    assert led.spent("dp1", "cohortA") == pytest.approx(0.1)


def test_epsilon_double_spend_across_restart(tmp_path):
    """A replayed journal keeps exhausted budgets exhausted, and a
    REJECTED charge journals nothing (restart does not resurrect it)."""
    led = pool_mod.EpsilonLedger(str(tmp_path), budget=0.5)
    led.charge("dp1", "cohortA", 0.5)
    with pytest.raises(pool_mod.EpsilonExhausted):
        led.charge("dp1", "cohortA", 0.5)
    # simulated process restart: fresh accountant over the same root
    led2 = pool_mod.EpsilonLedger(str(tmp_path), budget=0.5)
    assert led2.spent("dp1", "cohortA") == pytest.approx(0.5)
    with pytest.raises(pool_mod.EpsilonExhausted):
        led2.charge("dp1", "cohortA", 0.5)
    assert led2.check("dp1", "cohortA", 0.5) is False
    assert led2.check("dp2", "cohortA", 0.5) is True


def test_epsilon_ledger_survives_torn_tail(tmp_path):
    """A crash mid-append leaves a torn JSON tail; replay drops it and
    every complete event before it stays spent."""
    led = pool_mod.EpsilonLedger(str(tmp_path), budget=1.0)
    led.charge("dp1", "cohortA", 0.5)
    with open(led._ledger_path, "a", encoding="utf-8") as f:
        f.write('{"ev": "consume", "dp": "dp1", "coh')  # torn mid-write
    led2 = pool_mod.EpsilonLedger(str(tmp_path), budget=1.0)
    assert led2.spent("dp1", "cohortA") == pytest.approx(0.5)
    led2.charge("dp1", "cohortA", 0.5)  # the torn event never counted
    with pytest.raises(pool_mod.EpsilonExhausted):
        led2.charge("dp1", "cohortA", 0.01)


# -- decode modes over the frequency grid -----------------------------------

def _dv(counts):
    c = np.asarray(counts, dtype=np.int64)
    return st.DecryptedVector(values=c, found=np.ones_like(c, dtype=bool),
                              is_zero=(c == 0))


def test_decode_median_and_quantiles():
    # histogram over values 10..14: data = 11,11,11,12,14,14 (total 6)
    dv = _dv([0, 3, 1, 0, 2])
    assert st.decode("median", dv, 10, 14) == 11
    assert st.decode("quantile", dv, 10, 14) == 11     # bare = median
    assert st.decode("quantile:0.5", dv, 10, 14) == 11
    assert st.decode("quantile:0.01", dv, 10, 14) == 11
    assert st.decode("quantile:0.999", dv, 10, 14) == 14
    assert st.decode("quantile:1.0", dv, 10, 14) == 14


def test_decode_top_k_order_and_ties():
    dv = _dv([2, 5, 0, 5, 1])
    # count desc, value asc on ties; zero-count values never appear
    assert st.decode("top_k:3", dv, 0, 4) == [1, 3, 0]
    assert st.decode("top_k", dv, 0, 4) == [1]         # bare = k=1
    assert st.decode("top_k:99", dv, 0, 4) == [1, 3, 0, 4]


def test_decode_modes_sparse_sentinels():
    """Empty-window sentinels mirror decode_grouped's ambiguity table:
    order statistics of nothing are None, top-k of nothing is []."""
    empty = _dv([0, 0, 0, 0])
    assert st.decode("median", empty, 0, 3) is None
    assert st.decode("quantile:0.9", empty, 0, 3) is None
    assert st.decode("top_k:2", empty, 0, 3) == []
    one = _dv([1])
    with pytest.raises(ValueError):
        st.decode("quantile:0.0", one, 0, 0)
    with pytest.raises(ValueError):
        st.decode("quantile:1.5", one, 0, 0)
    with pytest.raises(ValueError):
        st.decode("top_k:0", one, 0, 0)


def test_decode_grouped_accepts_decode_modes():
    # group 0 histogram [2, 0, 1] -> median 0; group 1 all-zero -> None
    vals = np.asarray([2, 0, 1, 0, 0, 0], dtype=np.int64)
    dv = st.DecryptedVector(values=vals, found=np.ones(6, dtype=bool),
                            is_zero=(vals == 0))
    out = st.decode_grouped("median", dv, np.asarray([[0], [1]]), 0, 2)
    assert out == {(0,): 0, (1,): None}


def test_decode_modes_exported():
    assert set(st.DECODE_MODES) == {"quantile", "median", "top_k"}


# -- expired-pane subtraction: exact bytes at the crypto level --------------

def _random_ct_stack(k: int, v: int, seed: int) -> np.ndarray:
    """(k, V, 2, 3, 16) stack of REAL curve points shaped like per-pane
    folds (test_topology.py's helper — fixed-base multiples of G1)."""
    rng = np.random.default_rng(seed)
    scalars = rng.integers(1, 2 ** 31, size=(k * v * 2,))
    limbs = np.stack([eg.secret_to_limbs(int(s)) for s in scalars])
    pts = np.asarray(eg.fixed_base_mul(eg.BASE_TABLE.table, limbs))
    return pts.reshape(k, v, 2, 3, 16).astype(np.uint32)


def test_expired_pane_subtraction_byte_identical():
    """window - expired + added, canonicalized, equals a from-scratch
    fold of the new window BYTE for byte (abelian cancellation mod p +
    canon_points collapsing the representation)."""
    stack = _random_ct_stack(k=5, v=3, seed=13)
    # slide by one: fold(0..3) - pane0 + pane4 == fold(1..4)
    w03 = jnp.asarray(np.asarray(topo.fold_cts(stack[0:4])))
    cur = eg.ct_sub(w03, jnp.asarray(stack[0]))
    cur = eg.ct_add(cur, jnp.asarray(stack[4]))
    delta = np.asarray(topo.canon_points(cur))
    scratch = np.asarray(topo.fold_cts(stack[1:5]))
    assert delta.tobytes() == scratch.tobytes()


def test_multi_pane_expiry_byte_identical():
    """A 2-pane slide (expire two, add two) is just as exact — the delta
    chain's length never accumulates representation error."""
    stack = _random_ct_stack(k=6, v=2, seed=29)
    cur = jnp.asarray(np.asarray(topo.fold_cts(stack[0:4])))  # panes 0..3
    for pid in (0, 1):
        cur = eg.ct_sub(cur, jnp.asarray(stack[pid]))
    for pid in (4, 5):
        cur = eg.ct_add(cur, jnp.asarray(stack[pid]))
    delta = np.asarray(topo.canon_points(cur))
    scratch = np.asarray(topo.fold_cts(stack[2:6]))
    assert delta.tobytes() == scratch.tobytes()


def test_subtract_to_empty_then_rebuild():
    """Subtracting every pane back out returns the identity; re-adding a
    pane matches that pane's own canonical fold."""
    stack = _random_ct_stack(k=3, v=2, seed=31)
    cur = jnp.asarray(np.asarray(topo.fold_cts(stack)))
    for pid in range(3):
        cur = eg.ct_sub(cur, jnp.asarray(stack[pid]))
    rebuilt = np.asarray(topo.canon_points(
        eg.ct_add(cur, jnp.asarray(stack[1]))))
    lone = np.asarray(topo.canon_points(jnp.asarray(stack[1])))
    assert rebuilt.tobytes() == lone.tobytes()


# -- full-pipeline streaming (proofs on): identity + reuse ------------------
# Heavy compiles: slow tier only, one shared cluster (test_service_proofs
# pattern — these must run in their own process on CPU).

@pytest.fixture(scope="module")
def cluster_stream():
    from drynx_tpu.service.service import LocalCluster

    return LocalCluster(n_cns=2, n_dps=2, n_vns=2, seed=1, dlog_limit=4000)


PW, W, V = 8, 3, 8


def _mk_engine(cluster, stream_id, **kw):
    from drynx_tpu.service.streaming import StreamEngine

    return StreamEngine(cluster, "frequency_count", 0, V - 1,
                        stream_id=stream_id, pane_width=PW, window_panes=W,
                        ranges=[(16, 2)] * V, proofs=1, seed=9, **kw)


def _mk_rows(cluster, n_panes, seed):
    rng = np.random.default_rng(seed)
    return {d.name: rng.integers(0, V, size=(n_panes, PW)).astype(np.int64)
            for d in cluster.dp_idents}


@pytest.mark.slow
def test_stream_delta_matches_scratch_at_1_2_4_pane_slides(cluster_stream):
    cl = cluster_stream
    from drynx_tpu.server.transcript import transcript_digest

    rows = _mk_rows(cl, 8, seed=0)
    eng = _mk_engine(cl, "s-ident")
    sealed = 0
    for slide in (1, 1, 2, 4):
        eng.feed({n: r[sealed:sealed + slide].reshape(-1)
                  for n, r in rows.items()})
        adv = eng.advance()
        sealed += slide
        first = max(0, sealed - W)
        assert adv.window == (first, sealed - 1)
        assert adv.survey_id == f"s-ident-w{first}-{sealed - 1}"
        # ground truth: plain counts over the window's rows
        truth = Counter()
        for r in rows.values():
            truth.update(r[first:sealed].reshape(-1).tolist())
        assert adv.result == {v: truth.get(v, 0) for v in range(V)}
        assert adv.block is not None
        assert all(p.block is not None for p in eng._panes)

        # from-scratch control: a FRESH engine re-fed every row produces
        # the same survey id, result, decrypted bytes, advance transcript
        # AND per-pane seal-time transcripts (stream-stable pane sids)
        dig = transcript_digest(cl.vns, adv.survey_id)
        pane_digs = [transcript_digest(cl.vns, eng.pane_sid(p))
                     for p in range(first, sealed)]
        scratch = _mk_engine(cl, "s-ident")
        scratch.feed({n: r[:sealed].reshape(-1) for n, r in rows.items()})
        sadv = scratch.advance()
        assert sadv.survey_id == adv.survey_id
        assert sadv.result == adv.result
        assert (sadv.decrypted.values.tobytes()
                == adv.decrypted.values.tobytes())
        assert transcript_digest(cl.vns, sadv.survey_id) == dig
        assert [transcript_digest(cl.vns, scratch.pane_sid(p))
                for p in range(first, sealed)] == pane_digs
        # the scratch engine's seal-time deliveries hit the VN
        # VerifyCache (same pane sid, same payload bytes): zero fresh
        # pairings
        assert scratch.counters["pane_verifies"] == 0

    assert eng.counters["advances"] == 4
    assert eng.counters["panes_sealed"] == 8
    # each sealed pane proven once per DP and verified at most once per
    # DP, at seal time; an advance re-ships NOTHING for carried panes
    n_dps = len(cl.dp_idents)
    assert eng.counters["proofs_created"] == 8 * n_dps
    assert eng.counters["proofs_reused"] == 0
    assert eng.counters["pane_verifies"] <= 8 * n_dps


@pytest.mark.slow
def test_pane_proof_reuse_byte_identical_across_restart(cluster_stream,
                                                        tmp_path):
    cl = cluster_stream
    from drynx_tpu.server.transcript import transcript_digest

    rows = _mk_rows(cl, 3, seed=4)
    db = ProofDB(str(tmp_path / "panes.db"))
    e1 = _mk_engine(cl, "s-reuse", pane_db=db)
    e1.feed({n: r.reshape(-1) for n, r in rows.items()})
    a1 = e1.advance()
    n_dps = len(cl.dp_idents)
    assert e1.counters["proofs_created"] == 3 * n_dps
    assert e1.counters["proofs_reused"] == 0
    blobs1 = {(p.pane_id, d): b for p in e1._panes
              for d, b in p.blobs.items()}
    dig1 = transcript_digest(cl.vns, a1.survey_id)
    pane_digs1 = [transcript_digest(cl.vns, e1.pane_sid(p))
                  for p in range(3)]
    db.close()

    # restart: reopened store, fresh engine, same stream id + rows
    db2 = ProofDB(str(tmp_path / "panes.db"))
    assert any(k.startswith(b"pane:") for k in db2.keys())
    assert db2.get(pane_key("s-reuse", 0, cl.dp_idents[0].name)) is not None
    e2 = _mk_engine(cl, "s-reuse", pane_db=db2)
    e2.feed({n: r.reshape(-1) for n, r in rows.items()})
    a2 = e2.advance()
    assert e2.counters["proofs_created"] == 0
    assert e2.counters["proofs_reused"] == 3 * n_dps
    for p in e2._panes:
        assert p.proofs_reused
        for d, b in p.blobs.items():
            assert b == blobs1[(p.pane_id, d)]
    assert a2.survey_id == a1.survey_id
    assert a2.result == a1.result
    assert transcript_digest(cl.vns, a2.survey_id) == dig1
    assert [transcript_digest(cl.vns, e2.pane_sid(p))
            for p in range(3)] == pane_digs1


@pytest.mark.slow
def test_scheduler_advance_lane_and_epsilon_admission(cluster_stream,
                                                      tmp_path):
    """open_stream/advance_stream round-trip through the scheduler's
    advance fast lane; an exhausted budget is a typed rejection AT
    SUBMIT — nothing queues, earlier results stand."""
    from drynx_tpu.server import admission as adm
    from drynx_tpu.server.scheduler import SurveyServer

    cl = cluster_stream
    srv = SurveyServer(cl, pipeline=False)
    led = pool_mod.EpsilonLedger(str(tmp_path), budget=0.02)
    eng = _mk_engine(cl, "s-sched", epsilon_ledger=led,
                     epsilon_per_advance=0.01)
    assert srv.open_stream(eng, prewarm=False) is eng
    rows = _mk_rows(cl, 2, seed=7)

    t1 = srv.advance_stream("s-sched",
                            {n: r[0] for n, r in rows.items()})
    srv.drain()
    r1 = srv.results()[t1]
    assert r1.window == (0, 0)
    truth = Counter()
    for r in rows.values():
        truth.update(r[0].tolist())
    assert r1.result == {v: truth.get(v, 0) for v in range(V)}

    t2 = srv.advance_stream("s-sched",
                            {n: r[1] for n, r in rows.items()})
    srv.drain()
    assert srv.results()[t2].window == (0, 1)

    # budget 0.02 at 0.01/advance: the third advance rejects at submit
    with pytest.raises(adm.EpsilonExhausted):
        srv.advance_stream("s-sched", {n: r[1] for n, r in rows.items()})
    assert not srv._advance            # nothing queued by the rejection
    assert led.counters["rejections"] == 1
    assert eng.counters["advances"] == 2
    with pytest.raises(KeyError):
        srv.advance_stream("no-such-stream")
