"""PR-11 tree-topology rosters (drynx_tpu/service/topology.py).

The tree overlay replaces the root CN's O(n) star fan-in with O(log n)
relay hops, and its correctness rests on one algebraic contract: the
ciphertext group is abelian mod p, so ANY fold grouping yields the same
group element, and canon_points collapses every projective representative
of that element to identical bytes. This file proves the contract at
three levels — pure layout math, device folds, and full surveys over
real sockets (tree vs star must agree on results, responder sets, and VN
proof transcripts) — plus the PR's satellites: relay-failure isolation
at depth and idempotent survey_dp re-entry.
"""
import dataclasses
import json
import threading

import numpy as np
import pytest

from drynx_tpu.resilience import policy as rp
from drynx_tpu.resilience.faults import FaultPlan, set_fault_plan
from drynx_tpu.service import topology as topo
from drynx_tpu.service.node import (DrynxNode, RemoteClient, Roster,
                                    RosterEntry)
from drynx_tpu.service.transport import set_conn_pool, unpack_array


@pytest.fixture(autouse=True)
def _clean_process_globals():
    set_fault_plan(None)
    set_conn_pool(None)
    yield
    set_fault_plan(None)
    set_conn_pool(None)


# -- layout math (pure python, no jax) --------------------------------------

@pytest.mark.parametrize("n,b", [(1, 1), (2, 2), (5, 2), (10, 2),
                                 (16, 4), (37, 5), (256, 8)])
def test_tree_layout_partitions_roster(n, b):
    """The forest roots' subtrees partition the index space exactly, and
    children/parent are mutual inverses — every index is dispatched once
    whatever level it sits at."""
    seen = [j for i in topo.roots(n, b) for j in topo.subtree(i, n, b)]
    assert sorted(seen) == list(range(n))
    assert len(seen) == n                      # no index reached twice
    for j in range(n):
        p = topo.parent(j, b)
        if p is None:
            assert j in topo.roots(n, b)
        else:
            assert j in topo.children(p, n, b)
    d = topo.depth(n, b)
    assert d >= 1 and (n <= b) == (d == 1)


def test_survivor_layout_reparents_dead_relay_subtree():
    """PR 17 failover: dropping a dead interior relay from the roster
    re-derives a valid tree over the survivors — its former descendants
    land under live parents, the layout still partitions the index
    space, and the result depends only on WHO survived (roster order),
    never on probe return order."""
    order = [f"dp{i}" for i in range(10)]
    b = topo.tree_fanout(10)                       # 4: dp1 is interior
    assert topo.children(1, 10, b)                 # it really has a subtree
    alive = [n for n in order if n != "dp1"]
    layout = topo.survivor_layout(order, set(alive))
    assert layout == alive                         # roster order kept
    # probe order must not matter
    assert topo.survivor_layout(order, reversed(alive)) == layout
    # the re-derived tree over the survivors is a full partition again
    n2, b2 = len(layout), topo.tree_fanout(len(layout))
    seen = [j for i in topo.roots(n2, b2)
            for j in topo.subtree(i, n2, b2)]
    assert sorted(seen) == list(range(n2))
    assert topo.survivor_layout(order, set()) == []
    assert topo.survivor_layout(order, order) == order


def test_tree_fanout_auto_clamps_and_env(monkeypatch):
    monkeypatch.delenv(topo.ENV_FANOUT, raising=False)
    assert topo.tree_fanout(0) == 1 and topo.tree_fanout(1) == 1
    assert topo.tree_fanout(4) == rp.TREE_FANOUT_MIN
    assert topo.tree_fanout(9) == 3            # ceil(sqrt(9))
    assert topo.tree_fanout(256) == rp.TREE_FANOUT_MAX  # 16 clamped to 8
    monkeypatch.setenv(topo.ENV_FANOUT, "5")
    assert topo.tree_fanout(256) == 5
    monkeypatch.setenv(topo.ENV_FANOUT, "0")
    assert topo.tree_fanout(256) == 1          # floor at 1, never 0


def test_topology_mode_kill_switch(monkeypatch):
    monkeypatch.delenv(topo.ENV_TOPOLOGY, raising=False)
    assert topo.topology_mode() == "tree"
    monkeypatch.setenv(topo.ENV_TOPOLOGY, " STAR ")
    assert topo.topology_mode() == "star"
    monkeypatch.setenv(topo.ENV_TOPOLOGY, "ring")   # typo degrades to
    assert topo.topology_mode() == "tree"           # the default


# -- canonical folds: the mod-p associativity contract ----------------------

def _random_ct_stack(k: int, v: int, seed: int) -> np.ndarray:
    """(k, V, 2, 3, 16) stack of REAL curve points (fixed-base multiples
    of G1 — cheap, no 20s pub-table build), shaped like DP ciphertexts."""
    from drynx_tpu.crypto import elgamal as eg

    rng = np.random.default_rng(seed)
    scalars = rng.integers(1, 2 ** 31, size=(k * v * 2,))
    limbs = np.stack([eg.secret_to_limbs(int(s)) for s in scalars])
    pts = np.asarray(eg.fixed_base_mul(eg.BASE_TABLE.table, limbs))
    return pts.reshape(k, v, 2, 3, 16).astype(np.uint32)


def test_fold_cts_mod_p_associativity_byte_identical():
    """Folding the same stack under three different groupings — tree
    halving, left-to-right serial, reversed serial — must land on
    byte-identical canonical tensors. This is the contract the tree/star
    transcript-identity gate rests on: grouping changes Jacobian Z slack,
    canon_points erases it."""
    from drynx_tpu.crypto import batching as B

    stack = _random_ct_stack(k=5, v=3, seed=7)
    tree = np.asarray(topo.fold_cts(stack))

    def serial(parts):
        acc = parts[0]
        for p in parts[1:]:
            acc = B.ct_add(acc, p)
        return np.asarray(topo.canon_points(acc))

    fwd = serial(list(stack))
    rev = serial(list(stack[::-1]))
    assert tree.tobytes() == fwd.tobytes() == rev.tobytes()
    # nested grouping, like a relay folding its subtree before the root
    # folds the relay partials
    sub = np.asarray(topo.fold_cts(stack[2:]))
    nested = np.asarray(topo.fold_cts(np.stack([stack[0], stack[1], sub])))
    assert nested.tobytes() == tree.tobytes()


def test_canon_points_idempotent_and_single_fold():
    stack = _random_ct_stack(k=1, v=2, seed=11)
    one = np.asarray(topo.fold_cts(stack))          # k=1: canon only
    assert one.tobytes() == np.asarray(topo.canon_points(one)).tobytes()
    assert one.shape == stack.shape[1:]


# -- compilecache: the TreeFold program axis --------------------------------

def test_registry_n_fold_adds_treefold_and_zero_is_identity():
    from drynx_tpu import compilecache as cc

    base = cc.Profile(n_cns=2, n_dps=4, n_values=3, u=4, l=2,
                      dlog_limit=100)
    zero = {s.name for s in cc.build_registry(base)}
    one = {s.name for s in cc.build_registry(
        dataclasses.replace(base, n_fold=1))}
    assert one == zero              # a 1-high stack never dispatches adds
    # k=9 (fanout-8 relay + its own contribution) folds at widths
    # {4,2,1}*V; 4*3=12 crosses the bucket boundary above the star
    # registry's n_values=3 aggregation add, so exactly ct_add@16 is new
    tree_specs = cc.build_registry(dataclasses.replace(base, n_fold=9))
    extra = [s for s in tree_specs if s.name not in zero]
    assert [s.name for s in extra] == ["bucketed:ct_add@16"]
    assert all(s.phase == "TreeFold" for s in extra)
    assert zero <= {s.name for s in tree_specs}   # star stays a subset


# -- real-socket surveys: tree vs star --------------------------------------

def _boot(tmp_path, roles, rng):
    """DrynxNode servers named <role><i> with per-role counters; returns
    (nodes, entries, datas-by-name)."""
    from drynx_tpu.crypto import elgamal as eg
    from drynx_tpu.resilience import RetryPolicy

    policy = RetryPolicy(connect_retries=1, backoff_s=0.02,
                         backoff_cap_s=0.05, jitter=0.0,
                         call_timeout_s=rp.CALL_TIMEOUT_S, seed=0)
    nodes, entries, datas, counts = [], [], {}, {}
    for role in roles:
        i = counts.get(role, 0)
        counts[role] = i + 1
        name = f"{role}{i}"
        x, pub = eg.keygen(rng)
        data = None
        if role == "dp":
            data = rng.integers(0, 10, size=(8,)).astype(np.int64)
            datas[name] = data
        n = DrynxNode(name, x, pub, data=data,
                      db_path=str(tmp_path / f"{name}.db"), policy=policy)
        n.start()
        entries.append(RosterEntry(name=name, role=role, host=n.address[0],
                                   port=n.address[1], public=pub))
        nodes.append(n)
    return nodes, entries, datas, policy


def test_tree_vs_star_same_result_fewer_root_bytes(tmp_path, monkeypatch):
    """A 3-level tree (7 DPs, fanout 2) and the star kill-switch must
    agree on the exact sum and the responder list, while the tree run
    lands strictly fewer bytes at the root CN — relays absorb their
    subtrees' payloads and forward one folded partial."""
    from drynx_tpu.crypto import elgamal as eg

    monkeypatch.setenv(topo.ENV_FANOUT, "2")
    rng = np.random.default_rng(41)
    nodes, entries, datas, policy = _boot(
        tmp_path, ["cn"] + ["dp"] * 7, rng)
    try:
        client = RemoteClient(Roster(entries), rng, policy=policy)
        client.broadcast_roster()
        dl = eg.DecryptionTable(limit=1000)
        want = int(sum(d.sum() for d in datas.values()))

        def run(sid):
            set_conn_pool(None)
            res = client.run_survey("sum", query_min=0, query_max=9,
                                    survey_id=sid, dlog=dl)
            return (res, list(client.last_responders),
                    dict(client.last_net.get("rx_by_node") or {}))

        res_t, resp_t, rx_t = run("tv-tree")
        monkeypatch.setenv(topo.ENV_TOPOLOGY, "star")
        res_s, resp_s, rx_s = run("tv-star")
        monkeypatch.delenv(topo.ENV_TOPOLOGY)
    finally:
        for n in nodes:
            n.stop()
    assert res_t == res_s == want
    assert resp_t == resp_s == [f"dp{i}" for i in range(7)]
    # bytes-at-root: the star root hears all 7 DP payloads, the tree
    # root only its 2 forest roots' folded partials
    assert 0 < rx_t["cn0"] < rx_s["cn0"]


def test_tree_relay_kill_degrades_only_that_node(tmp_path, monkeypatch):
    """FaultPlan-kill of a MID-TREE relay (dp2 under fanout 2 has the
    children dp6, dp7): only the killed node goes absent — the root
    re-dispatches its children as subtree roots — and the same plan
    yields the same responder set on a second survey across the same
    relay hops (seeded chaos stays deterministic at depth)."""
    from drynx_tpu.crypto import elgamal as eg

    monkeypatch.setenv(topo.ENV_FANOUT, "2")
    rng = np.random.default_rng(42)
    nodes, entries, datas, policy = _boot(
        tmp_path, ["cn"] + ["dp"] * 10, rng)
    try:
        client = RemoteClient(Roster(entries), rng, policy=policy)
        client.broadcast_roster()
        plan = FaultPlan(seed=5)
        plan.kill("dp2")
        set_fault_plan(plan)
        dl = eg.DecryptionTable(limit=1000)
        want = int(sum(d.sum() for n, d in datas.items() if n != "dp2"))
        outcomes = []
        for sid in ("kill-a", "kill-b"):
            res = client.run_survey("sum", query_min=0, query_max=9,
                                    survey_id=sid, dlog=dl,
                                    min_dp_quorum=8)
            outcomes.append((res, list(client.last_responders),
                             list(client.last_absent)))
    finally:
        for n in nodes:
            n.stop()
    for res, resp, absent in outcomes:
        assert res == want
        assert absent == ["dp2"]               # dp6/dp7 recovered
        assert resp == [f"dp{i}" for i in range(10) if i != 2]
    assert outcomes[0] == outcomes[1]          # deterministic at depth


@pytest.mark.slow
def test_tree_vs_star_vn_transcripts_byte_identical(tmp_path, monkeypatch):
    """Proofs-on acceptance gate: the committed VN audit bitmap (keys +
    verdict codes) must be byte-identical between the tree overlay —
    range proofs riding relay hops as batched blobs, hop aggregation
    proofs parent-verified, VN bitmaps collected up the VN tree — and
    the star kill-switch where every DP fires at the VNs directly."""
    import json as _json

    from drynx_tpu.crypto import elgamal as eg
    from drynx_tpu.proofs import requests as rq

    monkeypatch.setenv(topo.ENV_FANOUT, "2")
    rng = np.random.default_rng(43)
    nodes, entries, datas, policy = _boot(
        tmp_path, ["cn", "dp", "dp", "dp", "vn", "vn", "vn"], rng)
    try:
        client = RemoteClient(Roster(entries), rng, policy=policy)
        client.broadcast_roster()
        dl = eg.DecryptionTable(limit=1000)

        def run(sid):
            set_conn_pool(None)
            result, block = client.run_survey(
                "sum", query_min=0, query_max=9, proofs=True,
                ranges=[(4, 4)], survey_id=sid, dlog=dl, timeout=2400.0)
            norm = {k.replace(sid, "SID"): v
                    for k, v in block["bitmap"].items()}
            return result, _json.dumps(norm, sort_keys=True)

        res_t, tr_t = run("vt-tree")
        monkeypatch.setenv(topo.ENV_TOPOLOGY, "star")
        res_s, tr_s = run("vt-star")
        monkeypatch.delenv(topo.ENV_TOPOLOGY)
    finally:
        for n in nodes:
            n.stop()
    assert res_t == res_s == int(sum(d.sum() for d in datas.values()))
    assert tr_t == tr_s
    bm = json.loads(tr_t)
    assert bm and set(bm.values()) == {rq.BM_TRUE}


# -- satellite: idempotent survey_dp re-entry -------------------------------

def _dp_node(tmp_path):
    from drynx_tpu.crypto import elgamal as eg

    rng = np.random.default_rng(17)
    x, pub = eg.keygen(rng)
    _, cn_pub = eg.keygen(rng)
    node = DrynxNode("dp0", x, pub, data=np.arange(8, dtype=np.int64),
                     db_path=str(tmp_path / "dp0.db"))
    node.roster = Roster([
        RosterEntry(name="cn0", role="cn", host="127.0.0.1", port=0,
                    public=cn_pub),
        RosterEntry(name="dp0", role="dp", host="127.0.0.1", port=0,
                    public=pub)])
    return node


def test_survey_dp_reentry_replays_identical_bytes(tmp_path):
    """Re-entry of survey_dp for the same survey must replay the FIRST
    contribution's exact ciphertext bytes (one encryption ever — a fresh
    one would double-count under tree re-dispatch) and fire the range
    proof at most once."""
    node = _dp_node(tmp_path)
    computed, fired = [], []
    real = node._dp_contribution
    node._dp_contribution = lambda m: (computed.append(1), real(m))[1]
    node._fire_proof_request_async = lambda req: fired.append(req)
    msg = {"type": "survey_dp", "op": "sum", "survey_id": "dup-1",
           "query_min": 0, "query_max": 9, "proofs": False}
    r1 = node._h_survey_dp(dict(msg))
    r2 = node._h_survey_dp(dict(msg))
    assert np.asarray(unpack_array(r1["cts"])).tobytes() \
        == np.asarray(unpack_array(r2["cts"])).tobytes()
    assert len(computed) == 1 and not fired


def test_survey_dp_reentry_fires_proof_once_and_prunes(tmp_path):
    node = _dp_node(tmp_path)
    cts = np.zeros((1, 2, 3, 16), dtype=np.uint32)
    node._dp_contribution = lambda m: (cts, object())   # fake signed req
    fired = []
    node._fire_proof_request_async = lambda req: fired.append(req)
    msg = {"type": "survey_dp", "op": "sum", "survey_id": "dup-2",
           "query_min": 0, "query_max": 9, "proofs": True}
    for _ in range(3):
        node._h_survey_dp(dict(msg))
    assert len(fired) == 1
    # concurrent first entries: one computation, one firing
    node._dp_replies.clear()
    fired.clear()
    ts = [threading.Thread(
        target=lambda i=i: node._h_survey_dp(
            {**msg, "survey_id": "dup-3"})) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(fired) == 1
    # finished foreign surveys are pruned past the cache bound
    for i in range(2 * rp.DP_REPLY_CACHE_MAX):
        node._h_survey_dp({**msg, "proofs": False,
                           "survey_id": f"many-{i}"})
    assert len(node._dp_replies) <= rp.DP_REPLY_CACHE_MAX
