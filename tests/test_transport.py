"""TCP control plane + multi-node (in-process threads, real sockets) survey.

The real-TCP analogue of the reference's shell e2e tier (test/lib.sh boots
N server processes; client_run-survey drives a survey through them)."""
import numpy as np
import pytest

from drynx_tpu.crypto import elgamal as eg
from drynx_tpu.service.node import DrynxNode, RemoteClient, Roster, RosterEntry
from drynx_tpu.service.transport import Conn, NodeServer, pack_array, unpack_array


def test_transport_roundtrip():
    srv = NodeServer()
    srv.register("echo", lambda m: {"payload": m["payload"]})
    srv.start()
    c = Conn(srv.host, srv.port)
    assert c.call({"type": "echo", "payload": [1, 2, 3]})["payload"] == [1, 2, 3]
    with pytest.raises(RuntimeError):
        c.call({"type": "nope"})
    arr = np.arange(12, dtype=np.uint32).reshape(3, 4)
    packed = pack_array(arr)
    assert np.array_equal(unpack_array(packed), arr)
    c.close()
    srv.stop()


@pytest.mark.slow
def test_remote_survey_with_proofs(tmp_path):
    """The full proof pipeline over the real TCP path (round-1 gap: the
    distributed path carried no proofs): DP fires range proofs, root CN the
    aggregation proof, each CN a keyswitch proof; VNs verify with real
    verify_fns and the root VN's counter-gated commit yields an all-BM_TRUE
    bitmap."""
    from drynx_tpu.proofs import requests as rq

    rng = np.random.default_rng(33)
    nodes, entries, datas = [], [], []
    for i, role in enumerate(["cn", "cn", "dp", "vn", "vn"]):
        x, pub = eg.keygen(rng)
        data = None
        if role == "dp":
            data = rng.integers(0, 10, size=(8,)).astype(np.int64)
            datas.append(data)
        n = DrynxNode(f"{role}{i}", x, pub, data=data,
                      db_path=str(tmp_path / f"{role}{i}.db"))
        n.start()
        entries.append(RosterEntry(name=f"{role}{i}", role=role,
                                   host=n.address[0], port=n.address[1],
                                   public=pub))
        nodes.append(n)

    roster = Roster(entries)
    client = RemoteClient(roster, rng)
    client.broadcast_roster()
    # generous timeout: a cold CPU process compiles every proof kernel on
    # first use (tens of minutes at opt-level 0 on one core)
    result, block = client.run_survey(
        "sum", query_min=0, query_max=9, proofs=True, ranges=[(4, 4)],
        dlog=eg.DecryptionTable(limit=500), timeout=2400.0)
    want = int(sum(d.sum() for d in datas))
    assert result == want

    bitmap = block["bitmap"]
    # 1 range (1 DP) + 1 aggregation (root) + 2 keyswitch (2 CNs), per VN
    assert len(bitmap) == 4 * 2, bitmap
    assert set(bitmap.values()) == {rq.BM_TRUE}, bitmap

    # skipchain retrieval over TCP (reference serves genesis/latest/block/
    # proofs to REMOTE clients, services/service_skipchain.go:173-342)
    latest = client.get_latest()
    assert latest is not None and latest.hash() == block["block_hash"]
    genesis = client.get_genesis()
    assert genesis is not None and genesis.index == 0
    by_survey = client.get_block(survey_id="sv-remote")
    assert by_survey is not None and by_survey.hash() == latest.hash()
    assert client.get_block(index=10**6) is None
    stored = client.get_proofs("sv-remote")
    assert len(stored) == 4, sorted(stored)  # 4 proofs stored at the root VN
    assert all(len(v) > 0 for v in stored.values())
    client.close_db()
    for n in nodes:
        n.stop()


def test_remote_survey_log_reg(tmp_path):
    """log_reg over the REAL multi-process path (round-2 VERDICT missing #1):
    DPs hold (X, y) shards, the querier's trained weights must equal the
    clear-text twin bit-for-bit (identical decrypted ints)."""
    import jax.numpy as jnp

    from drynx_tpu.models import logreg as lr

    rng = np.random.default_rng(55)
    X = rng.normal(size=(24, 2))
    y = (X @ np.asarray([1.0, -0.5]) > 0).astype(np.int64)
    params = lr.LRParams(k=2, precision=1e2, max_iterations=10, step=0.1,
                         lambda_=1.0, n_features=2, n_records=24)
    shards = [lr.shard_for_dp(X, y, i, 2) for i in range(2)]

    nodes, entries = [], []
    roles = ["cn", "cn", "dp", "dp"]
    di = 0
    for i, role in enumerate(roles):
        x, pub = eg.keygen(rng)
        data = None
        if role == "dp":
            data = shards[di]
            di += 1
        n = DrynxNode(f"{role}{i}", x, pub, data=data,
                      db_path=str(tmp_path / f"{role}{i}.db"))
        n.start()
        entries.append(RosterEntry(name=f"{role}{i}", role=role,
                                   host=n.address[0], port=n.address[1],
                                   public=pub))
        nodes.append(n)

    client = RemoteClient(Roster(entries), rng)
    client.broadcast_roster()
    w = client.run_survey("log_reg", lr_params=params,
                          dlog=eg.DecryptionTable(limit=6000))

    agg = sum(np.asarray(lr.encode_clear(Xi, yi, params))
              for Xi, yi in shards)
    want = np.asarray(lr.train(lr.unpack(jnp.asarray(agg), params), params))
    np.testing.assert_allclose(np.asarray(w), want, rtol=0, atol=0)
    for n in nodes:
        n.stop()


def test_remote_survey_group_by(tmp_path):
    """Group-by over the REAL multi-process path (round-2 VERDICT missing
    #1): DPs hold (values, group_labels); per-group sums must match."""
    rng = np.random.default_rng(66)
    group_by = [[0, 1, 2]]
    dp_data = []
    nodes, entries = [], []
    for i, role in enumerate(["cn", "dp", "dp"]):
        x, pub = eg.keygen(rng)
        data = None
        if role == "dp":
            vals = rng.integers(0, 10, size=(12,)).astype(np.int64)
            groups = rng.integers(0, 3, size=(12, 1)).astype(np.int64)
            dp_data.append((vals, groups))
            data = (vals, groups)
        n = DrynxNode(f"{role}{i}", x, pub, data=data,
                      db_path=str(tmp_path / f"{role}{i}.db"))
        n.start()
        entries.append(RosterEntry(name=f"{role}{i}", role=role,
                                   host=n.address[0], port=n.address[1],
                                   public=pub))
        nodes.append(n)

    client = RemoteClient(Roster(entries), rng)
    client.broadcast_roster()
    result = client.run_survey("sum", query_min=0, query_max=9,
                               group_by=group_by,
                               dlog=eg.DecryptionTable(limit=500))
    for g in range(3):
        want = int(sum(v[gr[:, 0] == g].sum() for v, gr in dp_data))
        assert result[(g,)] == want, (g, result)
    for n in nodes:
        n.stop()


def test_remote_survey_rejects_missing_proofs(tmp_path):
    """The counter gate: end_verification on a survey whose proofs never
    arrived must refuse to commit a block (round-1 weakness #5)."""
    rng = np.random.default_rng(44)
    x, pub = eg.keygen(rng)
    vn = DrynxNode("vn0", x, pub, db_path=str(tmp_path / "vn0.db"))
    vn.start()
    entries = [RosterEntry(name="vn0", role="vn", host=vn.address[0],
                           port=vn.address[1], public=pub)]
    roster = Roster(entries)
    client = RemoteClient(roster, rng)
    client.broadcast_roster()
    from drynx_tpu.service.node import call_entry

    call_entry(entries[0], {"type": "vn_register", "survey_id": "svx",
                            "expected": 3, "proofs": False})
    with pytest.raises(RuntimeError, match="proofs received"):
        call_entry(entries[0], {"type": "end_verification",
                                "survey_id": "svx", "timeout": 1.0})
    vn.stop()


def test_remote_survey_sum(tmp_path):
    rng = np.random.default_rng(21)
    nodes = []
    entries = []
    datas = []
    for i, role in enumerate(["cn", "cn", "dp", "dp", "vn"]):
        x, pub = eg.keygen(rng)
        data = None
        if role == "dp":
            data = rng.integers(0, 10, size=(8,)).astype(np.int64)
            datas.append(data)
        n = DrynxNode(f"{role}{i}", x, pub, data=data,
                      db_path=str(tmp_path / f"{role}{i}.db"))
        n.start()
        entries.append(RosterEntry(name=f"{role}{i}", role=role,
                                   host=n.address[0], port=n.address[1],
                                   public=pub))
        nodes.append(n)

    roster = Roster(entries)
    client = RemoteClient(roster, rng)
    client.broadcast_roster()
    result = client.run_survey("sum", query_min=0, query_max=9,
                               dlog=eg.DecryptionTable(limit=500))
    want = int(sum(d.sum() for d in datas))
    assert result == want
    for n in nodes:
        n.stop()


def test_link_model_charges_wall_clock():
    """The sleep-based per-link model (reference drynx.toml Delay/Bandwidth)
    adds delay + bytes/bandwidth per message, and env config wires it into
    send_msg."""
    import time

    from drynx_tpu.service.transport import LinkModel

    m = LinkModel(delay_ms=5, bandwidth_mbps=8)   # 8 Mbps = 1 byte/us
    t0 = time.perf_counter()
    m.charge(10_000)                               # 5 ms + 10 ms
    dt = time.perf_counter() - t0
    assert 0.014 <= dt <= 0.5
    assert not LinkModel().active
    assert LinkModel(delay_ms=1).active and LinkModel(bandwidth_mbps=1).active


@pytest.mark.slow
def test_link_model_in_cluster_survey():
    """A LocalCluster with a link model pays the DP-upload link latency:
    uploads ride INDEPENDENT links in parallel (the reference's per-link
    model), so the DataCollection phase carries ONE delay + serialization,
    regardless of roster size."""
    from drynx_tpu.service.service import LocalCluster
    from drynx_tpu.service.transport import LinkModel

    cluster = LocalCluster(n_cns=2, n_dps=4, n_vns=0, seed=3,
                           dlog_limit=2000, link=LinkModel(delay_ms=50))
    sq = cluster.generate_survey_query("sum", query_min=0, query_max=10)
    res = cluster.run_survey(sq)
    phases = dict(res.timers.items())
    assert phases["DataCollectionProtocol"] >= 0.05
