"""TCP control plane + multi-node (in-process threads, real sockets) survey.

The real-TCP analogue of the reference's shell e2e tier (test/lib.sh boots
N server processes; client_run-survey drives a survey through them)."""
import numpy as np
import pytest

from drynx_tpu.crypto import elgamal as eg
from drynx_tpu.service.node import DrynxNode, RemoteClient, Roster, RosterEntry
from drynx_tpu.service.transport import Conn, NodeServer, pack_array, unpack_array


def test_transport_roundtrip():
    srv = NodeServer()
    srv.register("echo", lambda m: {"payload": m["payload"]})
    srv.start()
    c = Conn(srv.host, srv.port)
    assert c.call({"type": "echo", "payload": [1, 2, 3]})["payload"] == [1, 2, 3]
    with pytest.raises(RuntimeError):
        c.call({"type": "nope"})
    arr = np.arange(12, dtype=np.uint32).reshape(3, 4)
    packed = pack_array(arr)
    assert np.array_equal(unpack_array(packed), arr)
    c.close()
    srv.stop()


def test_remote_survey_sum(tmp_path):
    rng = np.random.default_rng(21)
    nodes = []
    entries = []
    datas = []
    for i, role in enumerate(["cn", "cn", "dp", "dp", "vn"]):
        x, pub = eg.keygen(rng)
        data = None
        if role == "dp":
            data = rng.integers(0, 10, size=(8,)).astype(np.int64)
            datas.append(data)
        n = DrynxNode(f"{role}{i}", x, pub, data=data,
                      db_path=str(tmp_path / f"{role}{i}.db"))
        n.start()
        entries.append(RosterEntry(name=f"{role}{i}", role=role,
                                   host=n.address[0], port=n.address[1],
                                   public=pub))
        nodes.append(n)

    roster = Roster(entries)
    client = RemoteClient(roster, rng)
    client.broadcast_roster()
    result = client.run_survey("sum", query_min=0, query_max=9,
                               dlog=eg.DecryptionTable(limit=500))
    want = int(sum(d.sum() for d in datas))
    assert result == want
    for n in nodes:
        n.stop()
