"""Typestate analysis pass: unit tests for the protocol engine, goldens
for the fixture package, and the dynamic conformance cross-check.

Engine unit tests build tiny synthetic projects with
ProjectInfo.from_sources (same idiom as test_determinism_analysis.py)
and inspect the four typestate project rules directly. The chaos-marker
test at the bottom is the dynamic half of the prover: it drives a pool
deposit/consume/crash-recover cycle, a checkpoint save/load/resume
cycle and a real TCP ConnPool conversation in a child process under
DRYNX_PROTO_TRACE=1 and asserts every observed per-instance event
sequence is accepted by the declared automata — if the static pass says
the tree honours the protocols, the running system must too.
"""
import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from drynx_tpu.analysis import RULES, ProjectInfo
from drynx_tpu.analysis.core import suppressed_at
from drynx_tpu.analysis.typestate import Typestate

pytestmark = pytest.mark.lint

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "drynx_tpu"
FIXTURE = REPO_ROOT / "tests" / "fixtures" / "lintpkg"
GOLDEN_TS = REPO_ROOT / "tests" / "fixtures" / "lintpkg_typestate.json"
GOLDEN_FLOW = REPO_ROOT / "tests" / "fixtures" / "lintpkg_proto_codeflow.json"

TS_RULES = {"atomic-durable-write", "slab-consumption-order",
            "conn-checkout-discipline", "seal-commit-once"}


def findings_of(pairs):
    """The four typestate project rules over a synthetic project, with
    noqa suppression applied — the analyze_project slice that matters
    here, without re-reading the tree from disk."""
    project = ProjectInfo.from_sources(
        [(rel, textwrap.dedent(src)) for rel, src in pairs])
    findings = []
    for rid in sorted(TS_RULES):
        findings.extend(RULES[rid].run_project(project))
    findings = [f for f in findings
                if not suppressed_at(f, project.modules)]
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


# -- atomic-durable-write ----------------------------------------------------

def test_in_place_durable_write_is_flagged():
    fs = findings_of([("drynx_tpu/a.py", """\
        import os

        def journal(root, entry):
            fh = open(os.path.join(root, "epsilon.jsonl"), "w")
            fh.write(entry)
            fh.close()
    """)])
    assert [f.rule for f in fs] == ["atomic-durable-write"]
    assert "in place" in fs[0].message


def test_rename_before_fsync_is_flagged():
    fs = findings_of([("drynx_tpu/a.py", """\
        import os

        def publish(root, payload):
            final = root + "/bench.jsonl"
            tmp = final + ".tmp"
            fh = open(tmp, "w")
            fh.write(payload)
            fh.close()
            os.replace(tmp, final)
    """)])
    assert [f.rule for f in fs] == ["atomic-durable-write"]
    assert "fsync" in fs[0].message


def test_full_atomic_dance_is_clean():
    assert findings_of([("drynx_tpu/a.py", """\
        import os

        def publish(root, payload):
            final = root + "/bench.jsonl"
            tmp = final + ".tmp"
            fh = open(tmp, "w")
            fh.write(payload)
            fh.flush()
            os.fsync(fh.fileno())
            fh.close()
            os.replace(tmp, final)
    """)]) == []


def test_tmp_write_that_never_publishes_is_flagged():
    fs = findings_of([("drynx_tpu/a.py", """\
        import os

        def stage(root, payload):
            fh = open(root + "/ledger.jsonl.tmp", "w")
            fh.write(payload)
            fh.flush()
            os.fsync(fh.fileno())
            fh.close()
    """)])
    assert [f.rule for f in fs] == ["atomic-durable-write"]
    assert "never published" in fs[0].message


def test_durable_append_requires_a_declared_replay_routine():
    src = """\
        import os

        def append(root, entry):
            fh = open(os.path.join(root, "events.jsonl"), "a")
            fh.write(entry)
            fh.flush()
            os.fsync(fh.fileno())
            fh.close()
    """
    fs = findings_of([("drynx_tpu/a.py", src)])
    assert [f.rule for f in fs] == ["atomic-durable-write"]
    assert "replay" in fs[0].message
    # the same module WITH a replay routine is the journal idiom: clean
    assert findings_of([("drynx_tpu/a.py", src + """\

        def replay_events(root):
            return []
    """)]) == []


def test_branch_join_keeps_the_unsynced_path_alive():
    # one arm fsyncs, the other does not: the join is a state-set union,
    # so the publish is still flagged for the dirty path
    fs = findings_of([("drynx_tpu/a.py", """\
        import os

        def publish(root, payload, flush):
            final = root + "/ledger.jsonl"
            tmp = final + ".tmp"
            fh = open(tmp, "w")
            fh.write(payload)
            if flush:
                fh.flush()
                os.fsync(fh.fileno())
            fh.close()
            os.replace(tmp, final)
    """)])
    assert [f.rule for f in fs] == ["atomic-durable-write"]


def test_scratch_writes_are_not_durable():
    assert findings_of([("drynx_tpu/a.py", """\
        def note(root, payload):
            fh = open(root + "/scratch.txt", "w")
            fh.write(payload)
            fh.close()
    """)]) == []


# -- slab-consumption-order --------------------------------------------------

def test_slab_read_before_ledger_append_is_flagged():
    fs = findings_of([("drynx_tpu/a.py", """\
        import os

        def _ledger_append(path, entry):
            return entry

        def consume(np, slab, ledger):
            claimed = slab + ".claim"
            os.rename(slab, claimed)
            arrs = np.load(claimed)
            _ledger_append(ledger, slab)
            os.unlink(claimed)
            return arrs
    """)])
    assert [f.rule for f in fs] == ["slab-consumption-order"]
    assert "journal" in fs[0].message


def test_slab_protocol_order_is_clean():
    assert findings_of([("drynx_tpu/a.py", """\
        import os

        def _ledger_append(path, entry):
            return entry

        def consume(np, slab, ledger):
            claimed = slab + ".claim"
            os.rename(slab, claimed)
            _ledger_append(ledger, slab)
            arrs = np.load(claimed)
            os.unlink(claimed)
            return arrs
    """)]) == []


def test_claimed_slab_never_unlinked_is_flagged():
    fs = findings_of([("drynx_tpu/a.py", """\
        import os

        def _ledger_append(path, entry):
            return entry

        def consume(np, slab, ledger):
            claimed = slab + ".claim"
            os.rename(slab, claimed)
            _ledger_append(ledger, slab)
            return np.load(claimed)
    """)])
    assert [f.rule for f in fs] == ["slab-consumption-order"]
    assert "unlink" in fs[0].message


def test_unlink_before_read_is_flagged():
    fs = findings_of([("drynx_tpu/a.py", """\
        import os

        def _ledger_append(path, entry):
            return entry

        def consume(np, slab, ledger):
            claimed = slab + ".claim"
            os.rename(slab, claimed)
            _ledger_append(ledger, slab)
            os.unlink(claimed)
            return np.load(claimed)
    """)])
    assert [f.rule for f in fs] == ["slab-consumption-order"]


# -- conn-checkout-discipline ------------------------------------------------

def test_checkout_without_release_is_flagged():
    fs = findings_of([("drynx_tpu/a.py", """\
        def fetch(pool, host, msg):
            conn = pool.get(host, 9000)
            return conn.call(msg)
    """)])
    assert [f.rule for f in fs] == ["conn-checkout-discipline"]
    assert "leak" in fs[0].message


def test_release_on_both_edges_is_clean():
    assert findings_of([("drynx_tpu/a.py", """\
        def fetch(pool, host, msg):
            conn = pool.get(host, 9000)
            try:
                reply = conn.call(msg)
            except OSError:
                pool.discard(conn)
                raise
            pool.put(conn)
            return reply
    """)]) == []


def test_exception_edge_leak_is_flagged():
    fs = findings_of([("drynx_tpu/a.py", """\
        def fetch(pool, host, msg):
            conn = pool.get(host, 9000)
            try:
                reply = conn.call(msg)
            except OSError:
                raise
            pool.put(conn)
            return reply
    """)])
    assert [f.rule for f in fs] == ["conn-checkout-discipline"]


def test_close_in_finally_covers_every_exit():
    # the broadcast_roster idiom: return inside try, close in finally
    assert findings_of([("drynx_tpu/a.py", """\
        from drynx_tpu.service.transport import Conn

        def send_one(host, msg):
            c = Conn(host, 9000)
            try:
                return c.call(msg)
            finally:
                c.close()
    """)]) == []


def test_reuse_after_transport_failure_is_flagged():
    fs = findings_of([("drynx_tpu/a.py", """\
        def fetch(pool, host, msg):
            conn = pool.get(host, 9000)
            try:
                reply = conn.call(msg)
            except OSError:
                reply = conn.call(msg)
                pool.discard(conn)
                return reply
            else:
                pool.put(conn)
                return reply
    """)])
    assert [f.rule for f in fs] == ["conn-checkout-discipline"]
    assert "transport failure" in fs[0].message


def test_returning_a_suspect_conn_is_flagged():
    fs = findings_of([("drynx_tpu/a.py", """\
        def fetch(pool, host, msg):
            conn = pool.get(host, 9000)
            try:
                reply = conn.call(msg)
            except OSError:
                pool.put(conn)
                raise
            else:
                pool.put(conn)
                return reply
    """)])
    assert [f.rule for f in fs] == ["conn-checkout-discipline"]
    assert "transport failure" in fs[0].message


def test_release_inside_a_helper_is_tracked():
    assert findings_of([("drynx_tpu/a.py", """\
        def _release(pool, conn):
            pool.put(conn)

        def fetch(pool, host, msg):
            conn = pool.get(host, 9000)
            reply = conn.call(msg)
            _release(pool, conn)
            return reply
    """)]) == []


def test_checkout_inside_a_helper_chains_to_the_caller_leak():
    fs = findings_of([("drynx_tpu/a.py", """\
        def _dial(pool, host):
            return pool.get(host, 9000)

        def fetch(pool, host, msg):
            conn = _dial(pool, host)
            return conn.call(msg)
    """)])
    assert [f.rule for f in fs] == ["conn-checkout-discipline"]
    # the chain walks through the helper: creation hop, call-site hop,
    # use, and the leaking exit
    assert len(fs[0].call_chain) >= 3
    assert any("_dial" in hop for hop in fs[0].call_chain)


# -- seal-commit-once --------------------------------------------------------

def test_double_put_under_one_pane_key_is_flagged():
    fs = findings_of([("drynx_tpu/a.py", """\
        from drynx_tpu.service.store import pane_key

        def seal(db, sid, blob):
            key = pane_key(sid, 0, "dp0")
            db.put(key, blob)
            db.put(key, blob)
    """)])
    assert [f.rule for f in fs] == ["seal-commit-once"]
    assert len(fs[0].call_chain) >= 3


def test_one_put_per_pane_key_is_clean():
    assert findings_of([("drynx_tpu/a.py", """\
        from drynx_tpu.service.store import pane_key

        def seal(db, sid, blobs):
            for pid, blob in blobs:
                db.put(pane_key(sid, pid, "dp0"), blob)
    """)]) == []


def test_resumed_checkpoint_blind_save_is_flagged():
    fs = findings_of([("drynx_tpu/a.py", """\
        from drynx_tpu.service.store import SurveyCheckpoint

        def resume(db, sid):
            ck = SurveyCheckpoint.load(db, sid)
            ck.save(db)
            return ck
    """)])
    assert [f.rule for f in fs] == ["seal-commit-once"]


def test_resumed_checkpoint_enter_then_save_is_clean():
    assert findings_of([("drynx_tpu/a.py", """\
        from drynx_tpu.service.store import SurveyCheckpoint

        def resume(db, sid):
            ck = SurveyCheckpoint.load(db, sid)
            ck.enter("collect")
            ck.save(db)
            return ck
    """)]) == []


def test_fresh_checkpoint_saves_freely():
    assert findings_of([("drynx_tpu/a.py", """\
        from drynx_tpu.service.store import SurveyCheckpoint

        def admit(db, sid):
            ck = SurveyCheckpoint(sid)
            ck.save(db)
            ck.enter("collect")
            ck.save(db)
            return ck
    """)]) == []


# -- suppression -------------------------------------------------------------

def test_noqa_at_the_violation_line_suppresses():
    assert findings_of([("drynx_tpu/a.py", """\
        import os

        def journal(root, entry):
            fh = open(os.path.join(root, "epsilon.jsonl"), "w")
            fh.write(entry)  # drynx: noqa[atomic-durable-write]
            fh.close()
    """)]) == []


def test_noqa_at_the_creation_anchor_suppresses():
    assert findings_of([("drynx_tpu/a.py", """\
        def fetch(pool, host, msg):
            conn = pool.get(host, 9000)  # drynx: noqa[conn-checkout-discipline]
            return conn.call(msg)
    """)]) == []


def test_protocol_marker_at_the_creation_site_suppresses():
    assert findings_of([("drynx_tpu/a.py", """\
        import os

        def journal(root, entry):
            # drynx: protocol[diagnostic mirror; the fsync'd copy is canonical]
            fh = open(os.path.join(root, "epsilon.jsonl"), "w")
            fh.write(entry)
            fh.close()
    """)]) == []


def test_protocol_marker_requires_a_reason():
    fs = findings_of([("drynx_tpu/a.py", """\
        import os

        def journal(root, entry):
            # drynx: protocol
            fh = open(os.path.join(root, "epsilon.jsonl"), "w")
            fh.write(entry)
            fh.close()
    """)])
    assert [f.rule for f in fs] == ["atomic-durable-write"]


def test_dual_anchors_cover_violation_and_creation():
    project = ProjectInfo.from_sources([("drynx_tpu/a.py", textwrap.dedent(
        """\
        def fetch(pool, host, msg):
            conn = pool.get(host, 9000)

            return conn.call(msg)
        """))])
    fs = list(RULES["conn-checkout-discipline"].run_project(project))
    assert len(fs) == 1
    anchor_lines = {line for _f, line in fs[0].anchors}
    assert 2 in anchor_lines      # creation site
    assert 4 in anchor_lines      # leaking exit


# -- fixture goldens ---------------------------------------------------------

def _fixture_findings():
    env = dict(os.environ, DRYNX_SKIP_JAX_INIT="1")
    proc = subprocess.run(
        [sys.executable, "-m", "drynx_tpu.analysis", "--format", "json",
         "--no-baseline", "tests/fixtures/lintpkg"],
        cwd=str(REPO_ROOT), capture_output=True, text=True, env=env)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    return json.loads(proc.stdout)["findings"]


def test_fixture_typestate_findings_match_golden():
    got = [f for f in _fixture_findings() if f["rule"] in TS_RULES]
    got.sort(key=lambda f: (f["file"], f["line"], f["rule"]))
    want = json.loads(GOLDEN_TS.read_text())
    assert got == want, (
        "typestate findings drifted from the golden; if intentional, "
        "regenerate tests/fixtures/lintpkg_typestate.json")


def test_fixture_sarif_codeflow_matches_golden():
    env = dict(os.environ, DRYNX_SKIP_JAX_INIT="1")
    proc = subprocess.run(
        [sys.executable, "-m", "drynx_tpu.analysis", "--format", "sarif",
         "--no-baseline", "tests/fixtures/lintpkg"],
        cwd=str(REPO_ROOT), capture_output=True, text=True, env=env)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    sarif = json.loads(proc.stdout)
    results = [r for r in sarif["runs"][0]["results"]
               if r["ruleId"] == "seal-commit-once"]
    assert len(results) == 1
    got = results[0]["codeFlows"]
    want = json.loads(GOLDEN_FLOW.read_text())
    assert got == want, (
        "the transition-site codeFlow drifted from the golden; if "
        "intentional, regenerate tests/fixtures/lintpkg_proto_codeflow.json")


def test_list_rules_groups_typestate_rules_under_their_engine():
    env = dict(os.environ, DRYNX_SKIP_JAX_INIT="1")
    proc = subprocess.run(
        [sys.executable, "-m", "drynx_tpu.analysis", "--list-rules"],
        cwd=str(REPO_ROOT), capture_output=True, text=True, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = proc.stdout.splitlines()
    assert "[typestate]" in lines
    section = lines[lines.index("[typestate]") + 1:]
    for rid in sorted(TS_RULES):
        line = next(ln for ln in section if rid in ln)
        assert "[project]" in line, line


# -- the real tree -----------------------------------------------------------

def test_real_tree_is_clean_and_fast():
    # fresh interpreter, the way check.sh runs it; the <5s budget is the
    # acceptance bar for the typestate pass alone on the full tree
    # (measured ~0.4s engine + ~1.7s project build on an idle core)
    prog = (
        "import json, sys, time\n"
        "from drynx_tpu.analysis import RULES, ProjectInfo\n"
        "from drynx_tpu.analysis.typestate import typestate_for\n"
        "project, errors = ProjectInfo.from_paths([%r])\n"
        "assert errors == []\n"
        "t0 = time.monotonic()\n"
        "ts = typestate_for(project)\n"
        "findings = []\n"
        "for rid in %r:\n"
        "    findings.extend(RULES[rid].run_project(project))\n"
        "elapsed = time.monotonic() - t0\n"
        "json.dump({'elapsed': elapsed,\n"
        "           'findings': [f.render() for f in findings],\n"
        "           'creations': len(ts.creation_sites),\n"
        "           'transitions': len(ts.transition_sites),\n"
        "           'protocols': sorted(ts.protocols_covered())},\n"
        "          sys.stdout)\n"
        % (str(PACKAGE), sorted(TS_RULES)))
    env = dict(os.environ, DRYNX_SKIP_JAX_INIT="1")
    proc = subprocess.run([sys.executable, "-c", prog], cwd=str(REPO_ROOT),
                          capture_output=True, text=True, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert out["findings"] == [], "\n".join(out["findings"])
    assert out["elapsed"] < 5.0, \
        f"typestate pass took {out['elapsed']:.1f}s (budget 5s)"
    # non-vacuity: a clean verdict only means something if the pass saw
    # the tree's resource surface — instances of every protocol family
    # and a healthy transition count
    assert len(out["protocols"]) >= 4, out["protocols"]
    assert out["creations"] >= 30, out["creations"]
    assert out["transitions"] >= 35, out["transitions"]


def test_changed_only_focus_is_fast_and_respected():
    # the marginal cost of the typestate stage under --changed-only:
    # build the project once (shared with every other pass), then time
    # ONLY the focused typestate run for a one-leaf change
    prog = (
        "import json, sys, time\n"
        "from drynx_tpu.analysis import RULES, ProjectInfo\n"
        "from drynx_tpu.analysis.typestate import typestate_for\n"
        "project, errors = ProjectInfo.from_paths([%r])\n"
        "assert errors == []\n"
        "focus = project.impacted_relpaths(['drynx_tpu/pool/store.py'])\n"
        "project.focus = focus\n"
        "t0 = time.monotonic()\n"
        "ts = typestate_for(project, frozenset(focus))\n"
        "findings = []\n"
        "for rid in %r:\n"
        "    findings.extend(RULES[rid].run_project(project))\n"
        "elapsed = time.monotonic() - t0\n"
        "json.dump({'elapsed': elapsed, 'n_focus': len(focus),\n"
        "           'findings': [f.render() for f in findings]},\n"
        "          sys.stdout)\n"
        % (str(PACKAGE), sorted(TS_RULES)))
    env = dict(os.environ, DRYNX_SKIP_JAX_INIT="1")
    proc = subprocess.run([sys.executable, "-c", prog], cwd=str(REPO_ROOT),
                          capture_output=True, text=True, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert out["findings"] == []
    assert out["n_focus"] >= 1
    assert out["elapsed"] < 2.0, \
        f"focused typestate stage took {out['elapsed']:.2f}s (budget 2s)"


def test_focus_narrows_reported_files():
    leak = textwrap.dedent("""\
        def fetch(pool, host, msg):
            conn = pool.get(host, 9000)
            return conn.call(msg)
    """)
    project = ProjectInfo.from_sources([("drynx_tpu/aa.py", leak),
                                        ("drynx_tpu/bb.py", leak)])
    project.focus = {"drynx_tpu/aa.py"}
    findings = list(RULES["conn-checkout-discipline"].run_project(project))
    assert {f.file for f in findings} == {"drynx_tpu/aa.py"}


# -- dynamic cross-check -----------------------------------------------------

_TRACE_CHILD = """\
import json, os, sys, tempfile
from drynx_tpu.analysis import prototrace
assert prototrace.installed(), "DRYNX_PROTO_TRACE=1 did not install"

import numpy as np
import jax
from drynx_tpu import pool as pool_mod
from drynx_tpu.crypto import elgamal as eg
from drynx_tpu.pool import replenish
from drynx_tpu.service.store import ProofDB, SurveyCheckpoint
from drynx_tpu.service.transport import Conn, ConnPool, NodeServer

with tempfile.TemporaryDirectory() as td:
    # atomic/journal/slab: deposit three slabs, consume across a
    # simulated crash (a second store over the same root replays the
    # fsync'd ledger before serving the remaining balance)
    rng = np.random.default_rng(42)
    x, pub = eg.keygen(rng)
    tbl = eg.pub_table(pub)
    root = os.path.join(td, "pool")
    pool = pool_mod.CryptoPool(root, slab_elems=8)
    dig = pool_mod.key_digest(tbl.table)
    k = jax.random.PRNGKey(0)
    for _ in range(3):
        k, s = jax.random.split(k)
        replenish.refill_slab(pool, s, tbl.table)
    pool.consume_dro(dig, 10)
    pool2 = pool_mod.CryptoPool(root, slab_elems=8)
    assert pool2.dro_balance(dig) == 8
    pool2.consume_dro(dig, 4)

    # ckpt: fresh save/enter cycle, then a load/enter/save resume
    db = ProofDB(os.path.join(td, "p.db"))
    ck = SurveyCheckpoint("chaos0")
    ck.enter("admitted")
    ck.save(db)
    ck.enter("collect")
    ck.save(db)
    resumed = SurveyCheckpoint.load(db, "chaos0")
    resumed.enter("collect")
    resumed.save(db)

# conn: a real TCP conversation through the pool — fresh checkout,
# idle reuses, an explicit discard, and a direct Conn close
srv = NodeServer()
srv.register("echo", lambda m: {"payload": m["payload"]})
srv.start()
cp = ConnPool()
for i in range(8):
    c = cp.get(srv.host, srv.port)
    assert c.call({"type": "echo", "payload": [i]})["payload"] == [i]
    cp.put(c)
c = cp.get(srv.host, srv.port)
cp.discard(c)
direct = Conn(srv.host, srv.port)
direct.call({"type": "echo", "payload": [99]})
direct.close()
cp.close_all()
srv.stop()

json.dump(prototrace.snapshot(), sys.stdout)
"""


@pytest.mark.chaos
def test_observed_lifecycles_conform_to_the_declared_automata():
    """Conformance cross-check: the static pass claims every resource in
    the tree follows its protocol. Drive the real implementations — the
    pool store's deposit/consume/crash-recover cycle, checkpoint
    save/load/resume, and a TCP ConnPool conversation — under the
    runtime recorder and assert the declared automata accept every
    observed per-instance event sequence."""
    env = dict(os.environ, DRYNX_PROTO_TRACE="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", _TRACE_CHILD],
                          cwd=str(REPO_ROOT), capture_output=True,
                          text=True, env=env, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    snap = json.loads(proc.stdout)

    from drynx_tpu.analysis import prototrace
    bad = prototrace.violations(snap)
    assert bad == [], "\n".join(bad)
    cover = prototrace.coverage(snap)
    # non-vacuity: the run must have exercised a meaningful slice of
    # the protocol surface, not an empty recorder
    assert len(cover) >= 3, cover
    assert sum(cover.values()) >= 20, cover
    assert cover.get("slab", 0) >= 3, cover
    assert cover.get("conn", 0) >= 8, cover
    assert cover.get("ckpt", 0) >= 2, cover
